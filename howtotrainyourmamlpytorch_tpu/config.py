"""Typed experiment configuration.

Replaces the reference's hydra-0.x single-file YAML (``config.yaml``) with a
typed dataclass schema + YAML file + ``key=value`` dotlist overrides, keeping
every key from the reference schema (SURVEY.md §2.8) plus the TPU-specific
additions (mesh shape, precision, remat policy). Named dataset and
inner-optimizer presets replace hydra's ``${omniglot}`` / ``${gd}`` node
interpolation (reference ``config.yaml:14,68``) and class-path instantiation
(reference ``few_shot_learning_system.py:87-88``).
"""

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import yaml

from . import exit_codes

# ---------------------------------------------------------------------------


@dataclass
class DatasetConfig:
    # reference config.yaml:28-34
    name: str = "omniglot_dataset"
    path: str = "datasets/omniglot_dataset"


@dataclass
class InnerOptimConfig:
    # reference config.yaml:68-85 (`gd`/`rprop`/`adam` presets)
    kind: str = "sgd"
    lr: float = 0.1
    beta1: float = 0.5
    beta2: float = 0.5


INNER_OPTIM_PRESETS: Dict[str, InnerOptimConfig] = {
    "gd": InnerOptimConfig(kind="sgd", lr=0.1),
    "sgd": InnerOptimConfig(kind="sgd", lr=0.1),
    "rprop": InnerOptimConfig(kind="rprop", lr=0.1),
    "adam": InnerOptimConfig(kind="adam", lr=0.1, beta1=0.5, beta2=0.5),
}

# The valid Config.remat_policy spellings, "" = derive from the legacy
# remat_inner_steps boolean. Kept as a literal here (config.py stays
# jax-free); core/maml.py::apply_remat_policy owns the mapping onto
# jax.checkpoint(policy=...). jax's ``everything_saveable`` is deliberately
# NOT offered: measured on jax 0.4.37 it changes the PRIMAL loss under grad
# for this scanned second-order program family (toy meta-step: loss delta
# 1.7e-3, meta-grad cosine 0.913 vs every other policy's bitwise/1e-8
# agreement) — a remat policy that changes math is a correctness bug, and
# its A/B role (price the checkpoint wrapper itself) is covered by
# comparing "none" against the saveable policies.
REMAT_POLICIES = (
    "",
    "none",
    "full",
    "dots_saveable",
    "dots_with_no_batch_dims_saveable",
)

# Adaptation strategies (core/strategies.py). Train-time strategies select
# the inner-loop rollout the meta-objective differentiates through;
# "protonet" is forward-only (adaptation is one embedding forward + a
# class-prototype reduction — nothing to meta-train here) and so is valid
# only on the serving menu. Kept as literals here (config.py stays
# jax-free); core/strategies.py owns the math.
TRAIN_STRATEGIES = ("maml++", "fomaml", "anil")
SERVING_STRATEGIES = ("maml++", "fomaml", "anil", "protonet")
DEFAULT_STRATEGY = "maml++"

# Multi-tenant serving (serving/registry.py + serving/tenancy.py): the
# default tenant is the frontend's own restored checkpoint. Requests that
# omit the tenant field — and requests naming it explicitly — resolve to
# the same internal identity (None), so adaptation ids, cache keys, and
# session files are byte-identical to the pre-tenancy platform.
DEFAULT_TENANT = "default"


def strategy_kind(kind: str, strategy: str) -> str:
    """Program-key kind with the strategy component attached. The default
    strategy keeps the bare legacy spelling (``"train"``, ``"adapt"``) so a
    default-config run's planned sets, ledger rows, manifest program names
    and executable-store files are byte-identical to the pre-registry ones;
    every other strategy is an explicit suffix (``"train@anil"``)."""
    return kind if strategy == DEFAULT_STRATEGY else f"{kind}@{strategy}"


def kind_base(kind: str) -> str:
    """``"train@anil"`` -> ``"train"`` (the dispatch half of a kind)."""
    return kind.split("@", 1)[0]


def kind_strategy(kind: str) -> str:
    """``"train@anil"`` -> ``"anil"``; bare kinds are the default strategy."""
    return kind.split("@", 1)[1] if "@" in kind else DEFAULT_STRATEGY


DATASET_PRESETS: Dict[str, DatasetConfig] = {
    "omniglot": DatasetConfig(name="omniglot_dataset", path="datasets/omniglot_dataset"),
    "imagenet": DatasetConfig(
        name="mini_imagenet_full_size", path="datasets/mini_imagenet_full_size"
    ),
}


@dataclass
class ParallelConfig:
    """TPU mesh layout — no reference equivalent (single GPU hard-coded at
    ``train_maml_system.py:23``); SURVEY.md §2.11 requires a 2D (data x model)
    mesh API. The meta-batch shards over ``dp``; ``mp`` is exposed for
    parameter sharding of larger backbones."""

    dp: int = -1  # -1: use all visible devices
    mp: int = 1
    # Pipeline-parallel stage count — deliberate non-goal for this model
    # family (SURVEY.md §2.11 PP row): the 4-conv backbones fit on one chip
    # with room to spare, so splitting them into stages would only add
    # bubble overhead. The field exists as the stage-partition hook; any
    # value != 1 is rejected until a backbone warrants an implementation.
    pp: int = 1
    # shard tasks of one meta-batch across dp; meta-grads psum over the mesh.
    shard_meta_batch: bool = True
    # Shard conv kernels output-channel-parallel over ``mp`` (in addition to
    # the always-on column-parallel dense head). Requires the patches-GEMM
    # conv implementation (Config.conv_via_patches, auto-enabled): GSPMD's
    # convolution handler hard-crashes on this program family's sharded
    # convs, a dot_general contraction partitions fine (models/layers.py
    # conv2d ``via_patches`` note, parallel/mesh.py::_param_spec).
    tp_convs: bool = False

    def __post_init__(self):
        if self.pp != 1:
            raise ValueError(
                f"pipeline parallelism (pp={self.pp}) is not implemented: the "
                "reference's 4-conv backbones fit on a single chip (documented "
                "non-goal, docs/DESIGN.md); use dp/mp"
            )


@dataclass
class ServingConfig:
    """Adapt-as-a-service engine knobs (serving/ package) — no reference
    equivalent (the reference has no inference path at all). The workload
    shape is adapt-once / predict-many: a client uploads a small support set,
    the server runs the inner loop once, then answers query requests against
    the cached adapted weights."""

    # Compiled shape buckets for the flattened support size (n_way * k_shot)
    # and the flattened query count: requests are padded up to the smallest
    # bucket >= their actual size so novel request shapes reuse an existing
    # compiled program instead of triggering an XLA recompile. Padded samples
    # are masked out of the loss and the transductive-BN statistics, so
    # bucketing never changes predictions. A request larger than the largest
    # bucket compiles its exact shape on demand.
    support_buckets: List[int] = field(default_factory=lambda: [25, 50, 100, 200])
    query_buckets: List[int] = field(default_factory=lambda: [5, 15, 40, 100])
    # Micro-batching: concurrent same-bucket requests are stacked along the
    # task axis (the axis MAMLSystem vmaps over) and flushed as ONE device
    # dispatch when max_batch_size requests are queued or the oldest request
    # has waited batch_deadline_ms. The task axis is padded up to the nearest
    # power of two <= max_batch_size so batch sizes also reuse compiles.
    max_batch_size: int = 8
    batch_deadline_ms: float = 3.0
    # Continuous batching (serving/batcher.py): requests arriving while a
    # flush is in flight join the NEXT flush the moment the worker frees,
    # instead of waiting out their own deadline window — under load the
    # batcher runs back-to-back flushes that grow toward max_batch_size.
    # Light-load coalescing (deadline semantics for stragglers) unchanged.
    continuous_batching: bool = True
    # Fleet (serving/pool.py + serving/router.py): engine replicas behind
    # one frontend. 1 = the single-replica pre-fleet behavior; 0 = one
    # replica per visible local device; N>1 explicit. Replicas targeting
    # the same device share compiled programs (CPU correctness mode).
    replicas: int = 1
    # Router admission control: shed (HTTP 429 + Retry-After) when the
    # routed replica already holds this many undispatched requests —
    # BEFORE the request queues. 0 disables (the per-replica batcher's
    # max_queue_depth shed stays as the inner backstop).
    router_max_queued_per_replica: int = 0
    # Adapted-weight cache: content-addressed by (checkpoint fingerprint,
    # support-set digest); repeat clients skip the inner loop entirely.
    cache_max_bytes: int = 256 * 1024 * 1024
    cache_ttl_s: float = 600.0
    # Inner steps per adapt request; 0 = the config's eval horizon
    # (number_of_evaluation_steps_per_iter), matching eval_step exactly.
    adapt_steps: int = 0
    # The adaptation strategies this deployment serves (core/strategies.py):
    # each request may name one (validated against this set; the first entry
    # is the default for requests that don't). Every configured strategy's
    # (bucket x batch-bucket) program grid is planned, prewarmed, and
    # strict-guarded; a valid-but-unconfigured strategy in a request is an
    # unplanned program — strict mode rejects it instead of silently
    # compiling. The default ["maml++"] keeps the planned set, prewarm grid,
    # and every program key byte-identical to the pre-registry engine.
    strategies: List[str] = field(default_factory=lambda: ["maml++"])
    # HTTP front-end (scripts/serve.py)
    host: str = "127.0.0.1"
    port: int = 8100
    # per-phase latency ring-buffer length for the /metrics percentiles
    latency_window: int = 2048
    # Graceful drain (SIGTERM -> serving/server.py::begin_drain): how long
    # the process waits for in-flight + queued requests to complete before
    # giving up. A clean drain exits 0; deadline expiry exits
    # exit_codes.DRAIN_DEADLINE (77) so the supervisor knows the replica's
    # last seconds were lossy.
    drain_deadline_s: float = 30.0
    # Spill hot adapted sessions content-addressed to
    # <run>/saved_models/sessions/ at drain, and rehydrate them
    # (digest-verified, fingerprint-matched, TTL-honored) at startup — a
    # rolling restart costs cache warmth bookkeeping, never correctness.
    # Only active for run-dir engines (an engine with no run dir has
    # nowhere durable to spill).
    session_spill: bool = True
    # Multi-tenant registry (serving/registry.py + serving/tenancy.py):
    # path to a tenants.yaml mapping tenant ids to checkpoint run dirs.
    # None (default) = single-tenant mode, byte-identical to the
    # pre-tenancy engine (closed-over-state programs, unchanged digests/
    # planned sets); a run-dir engine also auto-detects
    # <run_dir>/tenants.yaml. With a registry the engine compiles
    # state-as-ARGUMENT programs under the SAME shape-keyed program set,
    # so every tenant shares the prewarmed executables — a cold tenant
    # costs one host->device page-in, never an XLA compile.
    tenant_registry: Optional[str] = None
    # WeightPager HBM byte budget for tenant master states resident on
    # device (the default tenant's state is pinned and NOT counted).
    # 0 = unbounded (eviction still fires on the watermark signal below).
    tenant_budget_bytes: int = 0
    # Evict the LRU tenant when the HBM watermark provider
    # (observability/memory.py) reports min headroom below this fraction;
    # 0 disables the watermark trigger (byte budget only).
    tenant_min_headroom_frac: float = 0.0
    # Per-tenant quotas (serving/tenancy.py::TenantQuotas), enforced at
    # admission with the router's shed contract (429 + honest
    # Retry-After). 0 disables the respective quota.
    tenant_max_inflight: int = 0
    tenant_rate_rps: float = 0.0
    tenant_max_resident_bytes: int = 0
    # Persistent adaptation sessions (serving/server.py::refine): an /adapt
    # request naming a session_id with refine=true runs the K-step rollout
    # FROM the session's cached fast weights instead of the masters —
    # update-in-place refinement. Off by default: the refine program grid
    # joins the planned sets / prewarm grid ONLY when enabled, so a
    # refine-off deployment is byte-identical to the pre-session engine.
    refine_enabled: bool = False
    # Guard: after every refinement the session's held-out probe is scored
    # (cross-entropy through the planned predict program). A non-finite
    # score, or a score worse than the last-good by more than this
    # tolerance, rolls the session back to its last-good fast weights.
    refine_regress_tol: float = 0.5
    # M consecutive rolled-back refinements quarantine the session: further
    # refine/predict answer 409 + Retry-After until a fresh (non-refine)
    # /adapt re-adapts it from the masters. Never silently-stale weights.
    refine_quarantine_after: int = 3
    # Bounded ring of previous last-good fast-weight snapshots kept per
    # session (walked if the committed weights themselves go non-finite;
    # also spilled with the session lineage across drains).
    refine_snapshot_ring: int = 2
    # Fraction of the FIRST refine request's support set held out as the
    # session's persistent scoring probe (never trained on thereafter).
    refine_holdout_frac: float = 0.25

    def __post_init__(self):
        self.support_buckets = sorted(int(b) for b in self.support_buckets)
        self.query_buckets = sorted(int(b) for b in self.query_buckets)
        if any(b <= 0 for b in self.support_buckets + self.query_buckets):
            raise ValueError("serving buckets must be positive")
        # normalize the strategy menu: dedupe preserving order (the first
        # entry is the default strategy), validate every name
        seen: List[str] = []
        for s in self.strategies:
            if s not in SERVING_STRATEGIES:
                raise ValueError(
                    f"serving.strategies entry {s!r} is not a known "
                    f"strategy; valid: {list(SERVING_STRATEGIES)}"
                )
            if s not in seen:
                seen.append(s)
        if not seen:
            raise ValueError("serving.strategies must name at least one strategy")
        self.strategies = seen
        if self.max_batch_size < 1:
            raise ValueError(f"max_batch_size must be >= 1, got {self.max_batch_size}")
        if self.batch_deadline_ms < 0:
            raise ValueError("batch_deadline_ms must be >= 0")
        if self.replicas < 0:
            raise ValueError(
                f"serving.replicas must be >= 0 (0 = one per device), "
                f"got {self.replicas}"
            )
        if self.router_max_queued_per_replica < 0:
            raise ValueError(
                "router_max_queued_per_replica must be >= 0 (0 = disabled), "
                f"got {self.router_max_queued_per_replica}"
            )
        if self.latency_window < 1:
            raise ValueError(f"latency_window must be >= 1, got {self.latency_window}")
        if self.drain_deadline_s <= 0:
            raise ValueError(
                f"drain_deadline_s must be > 0, got {self.drain_deadline_s}"
            )
        if self.tenant_budget_bytes < 0:
            raise ValueError(
                f"tenant_budget_bytes must be >= 0 (0 = unbounded), "
                f"got {self.tenant_budget_bytes}"
            )
        if not 0.0 <= self.tenant_min_headroom_frac < 1.0:
            raise ValueError(
                "tenant_min_headroom_frac must be in [0, 1) (0 = disabled), "
                f"got {self.tenant_min_headroom_frac}"
            )
        if self.tenant_max_inflight < 0:
            raise ValueError(
                f"tenant_max_inflight must be >= 0 (0 = disabled), "
                f"got {self.tenant_max_inflight}"
            )
        if self.tenant_rate_rps < 0:
            raise ValueError(
                f"tenant_rate_rps must be >= 0 (0 = disabled), "
                f"got {self.tenant_rate_rps}"
            )
        if self.tenant_max_resident_bytes < 0:
            raise ValueError(
                f"tenant_max_resident_bytes must be >= 0 (0 = disabled), "
                f"got {self.tenant_max_resident_bytes}"
            )
        if self.refine_regress_tol < 0:
            raise ValueError(
                f"refine_regress_tol must be >= 0, got {self.refine_regress_tol}"
            )
        if self.refine_quarantine_after < 1:
            raise ValueError(
                f"refine_quarantine_after must be >= 1, "
                f"got {self.refine_quarantine_after}"
            )
        if self.refine_snapshot_ring < 1:
            raise ValueError(
                f"refine_snapshot_ring must be >= 1, "
                f"got {self.refine_snapshot_ring}"
            )
        if not 0.0 < self.refine_holdout_frac < 1.0:
            raise ValueError(
                "refine_holdout_frac must be in (0, 1), "
                f"got {self.refine_holdout_frac}"
            )


@dataclass
class ObservabilityConfig:
    """Unified telemetry knobs (``observability/`` package; no reference
    equivalent — the reference logged epoch-level CSVs and nothing else).
    Enabled, the runner and serving frontend record per-step phase spans
    (data-wait / dispatch / settle / checkpoint / eval) into a bounded ring,
    snapshot phase histograms + throughput to ``logs/telemetry.jsonl``, and
    export a Chrome/Perfetto trace at run end. Disabled, every hook is a
    shared no-op object and no file is created — the run is bit-identical
    to a build without the subsystem (test-asserted)."""

    enabled: bool = True
    # per-phase histogram ring length (exact percentiles over this window)
    histogram_window: int = 2048
    # completed-span ring capacity; evictions counted, never unbounded growth
    trace_capacity: int = 8192
    # also snapshot every N settled steps (0 = per-epoch snapshots only).
    # Per-step snapshots are for short diagnostic runs; at 500 iters/epoch
    # the per-epoch cadence is the production default.
    snapshot_every_steps: int = 0
    # write logs/trace.json (Chrome trace-event JSON) when the run closes
    export_chrome_trace: bool = True
    # per-program compile ledger (observability/compile_ledger.py): every
    # XLA compile recorded to logs/compile_ledger.jsonl with lower/compile
    # seconds, persistent-cache hit/miss, and program FLOPs — the evidence
    # base the AOT/cold-start work (ROADMAP item 2) reads.
    compile_ledger: bool = True
    # per-device HBM watermark provider (observability/memory.py): live and
    # peak bytes-in-use + headroom embedded in every telemetry snapshot
    memory_watermarks: bool = True
    # headroom fraction below which a one-shot (per device) hbm_headroom_low
    # event lands in events.jsonl — the pre-OOM breadcrumb
    hbm_headroom_warn_frac: float = 0.05
    # request-scoped serving observability (observability/context.py): the
    # structured access log, one JSON line per request in logs/access.jsonl
    # (trace id, verb, bucket, flush batch, queue-wait/dispatch/total ms,
    # cache hit, outcome, breaker state)
    access_log: bool = True
    # fraction of OK requests logged — deterministic on the trace id, so
    # every process of a fleet keeps or drops the same request. Non-ok
    # outcomes are ALWAYS logged regardless (the chaos invariant).
    access_log_sample: float = 1.0

    def __post_init__(self):
        if self.histogram_window < 1:
            raise ValueError(
                f"observability.histogram_window must be >= 1, "
                f"got {self.histogram_window}"
            )
        if self.trace_capacity < 1:
            raise ValueError(
                f"observability.trace_capacity must be >= 1, "
                f"got {self.trace_capacity}"
            )
        if self.snapshot_every_steps < 0:
            raise ValueError(
                f"observability.snapshot_every_steps must be >= 0, "
                f"got {self.snapshot_every_steps}"
            )
        if not 0.0 <= self.hbm_headroom_warn_frac < 1.0:
            raise ValueError(
                f"observability.hbm_headroom_warn_frac must be in [0, 1), "
                f"got {self.hbm_headroom_warn_frac}"
            )
        if not 0.0 <= self.access_log_sample <= 1.0:
            raise ValueError(
                f"observability.access_log_sample must be in [0, 1], "
                f"got {self.access_log_sample}"
            )


@dataclass
class PrecisionConfig:
    """Mixed-precision policy knobs (``ops/precision.py``; ROADMAP item 3 —
    the 8%-MFU gap). Off (the default), every cast helper is the identity
    and training/serving are bit-identical to a build without the subsystem;
    the legacy top-level ``compute_dtype`` knob keeps its exact pre-policy
    per-forward-cast semantics. Enabled, the inner loop runs the principled
    bf16 policy: f32 master params/LSLR lrs in the TrainState, fast weights
    and inner forward/backward/update in ``compute_dtype`` (cast once at
    rollout entry), BN statistics and loss reductions in ``stat_dtype``,
    MSL-weighted outer loss and outer Adam in f32."""

    enabled: bool = False
    # inner-loop compute dtype when enabled ("bfloat16" | "float32";
    # float32 degenerates to the plain path — an A/B convenience)
    compute_dtype: str = "bfloat16"
    # BN-statistics / normalization reduction dtype: "float32" (the policy's
    # point) or "compute" (stats in the compute dtype — the A/B lever for
    # pricing what f32 statistics cost)
    stat_dtype: str = "float32"
    # Fold the BN scale/shift into the patches-GEMM epilogue for conv->BN
    # layers (models/layers.py::conv2d_bn_patches): one fat GEMM + one
    # fused multiply-add instead of conv then a 4-op normalize chain. Same
    # math up to f.p. reassociation (parity-tested); vgg backbone only.
    # Requires conv_via_patches (auto-enabled, mirroring parallel.tp_convs).
    fuse_conv_bn: bool = False

    def __post_init__(self):
        if self.compute_dtype not in ("bfloat16", "float32"):
            raise ValueError(
                f"precision.compute_dtype must be 'bfloat16' or 'float32', "
                f"got {self.compute_dtype!r}"
            )
        if self.stat_dtype not in ("float32", "compute"):
            raise ValueError(
                f"precision.stat_dtype must be 'float32' or 'compute', "
                f"got {self.stat_dtype!r}"
            )


@dataclass
class AotConfig:
    """AOT prewarm knobs (``compile/aot.py``; ROADMAP item 2 — kill the
    compile tax). Enabled, the runner and serving frontend lower+compile the
    *entire* strict-mode planned program set at startup — before the first
    step / first request — through the compile ledger (every compile timed,
    ``phase="prewarm"``), backed by the persistent XLA compilation cache
    (``utils/compcache.py``) so a restarted run or a freshly spawned replica
    pays tracing, not XLA. An executable-store manifest written alongside
    checkpoints (program key -> signature, jaxlib/device-kind/mesh
    fingerprint, cache digest) lets a fresh process verify it will hit warm
    before accepting work. Disabled (the default): zero files, no prewarm,
    programs stay the plain lazily-jitted objects they always were."""

    enabled: bool = False
    # bounded thread pool overlapping program compiles (XLA compiles release
    # the GIL, so overlap is real even on one core)
    max_workers: int = 4
    # per-program compile budget inside the pool; generous — a cold 20-way
    # second-order train program is minutes of XLA on a slow backend
    compile_timeout_s: float = 3600.0
    # write/read the prewarm manifest next to the checkpoints
    executable_store: bool = True
    # serving: prewarm on a background thread so the HTTP server binds
    # immediately and /healthz says 503 "warming" until the set is compiled;
    # False compiles synchronously before the frontend accepts work
    serving_background: bool = True

    def __post_init__(self):
        if self.max_workers < 1:
            raise ValueError(f"aot.max_workers must be >= 1, got {self.max_workers}")
        if self.compile_timeout_s <= 0:
            raise ValueError(
                f"aot.compile_timeout_s must be > 0, got {self.compile_timeout_s}"
            )


@dataclass
class WatchdogConfig:
    """Hang (wedge) supervisor knobs (``resilience/watchdog.py``). A device
    call that hangs instead of raising is invisible to every raise-based
    defense; the watchdog converts a zero-progress interval into thread-stack
    forensics + an emergency checkpoint + the distinct restartable exit code
    ``wedge_exit_code`` (76), which ``scripts/sweep.sh`` treats as
    restart-not-fail alongside the preemption code 75."""

    enabled: bool = True
    # zero-progress seconds before the runner is declared wedged. Progress =
    # a dispatched/settled train step, an eval batch, a checkpoint write —
    # so the budget must cover one XLA compile of the heaviest program
    # (epoch 0 of the 20-way configs runs minutes of compile on a cold
    # cache; sweep.sh's log-staleness kill uses 420s against coarser
    # evidence). Generous by default; drills override it down.
    deadline_s: float = 900.0
    # supervisor poll period; 0 = auto (deadline/10 clamped to [0.02s, 5s])
    poll_s: float = 0.0
    wedge_exit_code: int = exit_codes.WEDGED
    # serving-side supervision of the batcher flush workers: a flush that
    # hangs in device dispatch past serve_deadline_s with work queued behind
    # it exits wedge_exit_code so a supervisor restarts the server (the
    # breaker already fail-fasts *clients*; it cannot un-hang the worker).
    serve_enabled: bool = True
    serve_deadline_s: float = 600.0

    def __post_init__(self):
        if self.deadline_s <= 0:
            raise ValueError(
                f"resilience.watchdog.deadline_s must be > 0, got {self.deadline_s}"
            )
        if self.serve_deadline_s <= 0:
            raise ValueError(
                f"resilience.watchdog.serve_deadline_s must be > 0, "
                f"got {self.serve_deadline_s}"
            )
        if not 1 <= self.wedge_exit_code <= 125 or self.wedge_exit_code in (
            exit_codes.DIVERGED,
            exit_codes.PREEMPTED,
        ):
            # reusing the divergence or preemption code would make the sweep
            # misclassify a wedge
            raise ValueError(
                "resilience.watchdog.wedge_exit_code must be in [1, 125] and "
                f"distinct from {exit_codes.DIVERGED}/{exit_codes.PREEMPTED}, "
                f"got {self.wedge_exit_code}"
            )


@dataclass
class ResilienceConfig:
    """Fault tolerance knobs (resilience/ package; no reference equivalent —
    the reference crashes on the first NaN, corrupt checkpoint, or SIGKILL).
    Injection specs are OFF by default: with ``faults`` empty (and no
    ``HTYMP_FAULTS`` env var) every seam is inert and behavior is
    bit-identical to a build without the subsystem."""

    # --- NaN/Inf step sentinel (experiment/runner.py) ---
    # Detect a non-finite outer-step loss and discard that step (the state
    # before it is restored; the episode stream moves on past the poisoned
    # batch). Detection fetches each step's scalar loss with a ONE-STEP lag,
    # so one dispatch stays in flight and episode assembly still overlaps
    # device compute; disable to restore unbounded dispatch depth (and the
    # pre-resilience behavior of training straight through NaNs).
    nan_guard: bool = True
    # K: consecutive discarded steps before rolling the TrainState back to
    # the last good checkpointed state (kept in memory by the runner)
    max_consecutive_bad_steps: int = 3
    # each rollback multiplies the outer LR schedule by this factor
    # (MAMLSystem.scale_meta_lr) — NaNs from an optimization blow-up need a
    # smaller step, not the same one replayed
    rollback_lr_backoff: float = 0.5
    # M: rollbacks spent before the runner gives up with the permanent
    # exit code 3 (scripts/sweep.sh: diverged, do not restart)
    max_rollbacks: int = 2
    # --- preemption (experiment/runner.py) ---
    # SIGTERM/SIGINT -> emergency 'latest' checkpoint carrying the mid-epoch
    # iteration cursor, then exit with preemption_exit_code (75 =
    # EX_TEMPFAIL) — scripts/sweep.sh restarts it without burning an attempt
    preemption_save: bool = True
    preemption_exit_code: int = exit_codes.PREEMPTED
    # --- loader transient-I/O retry (data/loader.py) ---
    loader_io_retries: int = 2
    loader_io_backoff_s: float = 0.05
    # --- serving (serving/server.py, serving/batcher.py) ---
    request_deadline_s: float = 30.0  # per request; exceeded -> HTTP 504
    max_queue_depth: int = 64  # per batcher; beyond -> shed (503 + Retry-After)
    shed_retry_after_s: float = 1.0
    breaker_failure_threshold: int = 5
    breaker_cooldown_s: float = 10.0
    breaker_half_open_probes: int = 1
    # consecutive request-deadline timeouts that trip the breaker — the
    # wedged-backend (hang) signature, which never raises and so never feeds
    # breaker_failure_threshold. A separate knob, defaulted LOWER than the
    # failure threshold: every timeout already burns a full
    # request_deadline_s before the client hears anything, so a hung device
    # should go fast-503 after fewer events than instant raising failures
    breaker_timeout_threshold: int = 3
    # --- graftsan lock-discipline sanitizer (tools/graftsan) ---
    # Arm the runtime lock-order/held-across-blocking detector: every lock
    # built through the utils/locks.py factories becomes an instrumented
    # wrapper reporting graftsan_violation events (events.jsonl +
    # scripts/graftsan_report.py). Off (default) the factories return plain
    # stdlib primitives — bit-identical behavior, zero overhead. The
    # HTYMP_GRAFTSAN=1 env var arms process-wide without a config (how the
    # chaos campaign arms its subprocess episodes).
    sanitizer: bool = False
    # --- wedge watchdog (resilience/watchdog.py) ---
    watchdog: WatchdogConfig = field(default_factory=WatchdogConfig)
    # --- fault injection (resilience/faults.py; spec grammar documented
    # there; HTYMP_FAULTS env specs are merged in at injector build) ---
    faults: List[str] = field(default_factory=list)
    fault_seed: int = 0

    def __post_init__(self):
        # YAML / dotlist loads hand the nested block over as a plain dict
        if isinstance(self.watchdog, dict):
            self.watchdog = WatchdogConfig(**self.watchdog)
        self.faults = list(self.faults)
        # parse eagerly so a typo'd drill spec fails at config load, not
        # hours into the run it was meant to harden
        from .resilience.faults import FaultSpec  # local: keep resilience config-free

        for spec in self.faults:
            FaultSpec.parse(spec)
        for name in (
            "max_consecutive_bad_steps",
            "max_rollbacks",
            "loader_io_retries",
            "max_queue_depth",
        ):
            if getattr(self, name) < 0:
                raise ValueError(f"resilience.{name} must be >= 0, got {getattr(self, name)}")
        # match CircuitBreaker's own constructor contract so a bad value
        # bounces here, not at serving startup hours later
        for name in (
            "breaker_failure_threshold",
            "breaker_half_open_probes",
            "breaker_timeout_threshold",
        ):
            if getattr(self, name) < 1:
                raise ValueError(f"resilience.{name} must be >= 1, got {getattr(self, name)}")
        if not 0.0 < self.rollback_lr_backoff <= 1.0:
            raise ValueError(
                f"resilience.rollback_lr_backoff must be in (0, 1], "
                f"got {self.rollback_lr_backoff}"
            )


@dataclass
class AutoscaleConfig:
    """Fleet-supervisor knobs (``serving/autoscaler.py`` +
    ``scripts/fleet_serve.py``; no reference equivalent). OFF by default:
    with ``enabled`` false no supervisor exists, no fleet_state.json is
    written, and gateway/backend behavior is byte-identical to a build
    without the subsystem (test-pinned off-switch, like every sibling).

    The supervisor itself is import-light (stdlib-only, yaml-free) and
    takes these knobs as CLI flags — this block is their documented schema
    home for run configs and presets; the defaults here are pinned equal to
    ``autoscaler.Policy.DEFAULTS`` by test so the two can never drift.
    See docs/OPERATIONS.md "Autoscaling" for the signal→decision table."""

    enabled: bool = False
    # fleet size clamps: scale-down never drains below min_backends;
    # scale-up never spawns past max_backends (= the pre-provisioned slots)
    min_backends: int = 1
    max_backends: int = 4
    # reactive loop: one control tick per poll_interval_s; up_polls
    # consecutive breach ticks to scale up, down_polls consecutive clear
    # ticks to scale down (hysteresis), each direction with its own cooldown
    poll_interval_s: float = 2.0
    up_polls: int = 2
    down_polls: int = 5
    cooldown_up_s: float = 10.0
    cooldown_down_s: float = 60.0
    # scale signals: max per-backend batcher queue depth, gateway shed/429
    # rate over the tick, pager eviction delta, pager page-in p50 (0 = off)
    queue_high: float = 8.0
    queue_low: float = 1.0
    shed_high: float = 0.05
    evict_high: int = 5
    page_in_p50_high_ms: float = 0.0
    # spawn warm gate + graceful drain deadlines
    warm_timeout_s: float = 300.0
    warm_poll_s: float = 0.5
    drain_timeout_s: float = 60.0
    # crash-loop ladder: crash_max deaths inside crash_window_s quarantines
    # the slot (never respawned hot); retries back off exponentially from
    # backoff_base_s, capped at backoff_max_s
    crash_max: int = 3
    crash_window_s: float = 60.0
    backoff_base_s: float = 0.5
    backoff_max_s: float = 30.0
    # predictive loop: re-forecast the traffic mix from access.jsonl every
    # forecast_interval_s over a forecast_window_s sliding window; a retune
    # is parked for the NEXT spawn when it cuts padding waste by at least
    # retune_waste_improvement (absolute waste-fraction points)
    forecast_interval_s: float = 30.0
    forecast_window_s: float = 300.0
    forecast_min_requests: int = 20
    retune_waste_improvement: float = 0.10
    max_buckets: int = 4

    def __post_init__(self):
        if self.min_backends < 0:
            raise ValueError(
                f"autoscale.min_backends must be >= 0, got {self.min_backends}"
            )
        if self.max_backends < max(1, self.min_backends):
            raise ValueError(
                f"autoscale.max_backends must be >= max(1, min_backends), "
                f"got {self.max_backends}"
            )
        for name in ("up_polls", "down_polls", "crash_max"):
            if getattr(self, name) < 1:
                raise ValueError(
                    f"autoscale.{name} must be >= 1, got {getattr(self, name)}"
                )
        for name in (
            "poll_interval_s",
            "warm_timeout_s",
            "drain_timeout_s",
            "backoff_base_s",
            "crash_window_s",
        ):
            if getattr(self, name) <= 0:
                raise ValueError(
                    f"autoscale.{name} must be > 0, got {getattr(self, name)}"
                )


@dataclass
class Config:
    # --- data provider (reference config.yaml:11-20,63-65) ---
    num_dataprovider_workers: int = 4
    max_models_to_save: int = 5
    dataset: DatasetConfig = field(default_factory=DatasetConfig)
    # None = auto: True for mini-imagenet (whose class labels embed the
    # official split, "train/n...", reference data.py:185-196 + the
    # ${imagenet} config node), False otherwise. An explicit bool wins.
    sets_are_pre_split: Optional[bool] = None
    load_from_npz_files: bool = False  # unused in reference code; kept for schema parity
    load_into_memory: bool = True
    samples_per_iter: int = 1
    num_target_samples: int = 1
    reverse_channels: bool = False
    labels_as_int: bool = False
    reset_stored_filepaths: bool = False
    # where the dataset index JSONs are cached; empty = next to the dataset dir
    # (the reference location, data.py:252) — set this when the dataset lives
    # on a read-only mount.
    index_cache_dir: str = ""
    # optional override of the per-dataset class-split ratios (reference
    # hard-codes them per dataset, data.py:125,129); empty = dataset default.
    train_val_test_split: List[float] = field(default_factory=list)

    def __post_init__(self):
        # normalize so YAML round-trips compare equal
        self.train_val_test_split = list(self.train_val_test_split)
        if self.checkpoint_rotation not in ("latest", "best_val"):
            raise ValueError(
                f"checkpoint_rotation must be 'latest' or 'best_val', "
                f"got {self.checkpoint_rotation!r}"
            )
        if self.test_ensemble_top_k > 1 and self.checkpoint_rotation != "best_val":
            # with latest-N rotation the best-val epochs may already be
            # deleted, silently degrading the documented top-K-by-val-accuracy
            # ensemble semantics
            raise ValueError(
                "test_ensemble_top_k > 1 requires checkpoint_rotation='best_val' "
                "so the top validation checkpoints are actually retained"
            )
        if 0 < self.max_models_to_save < self.test_ensemble_top_k:
            # (max_models_to_save <= 0 disables rotation = keep ALL
            # checkpoints, so any K is satisfiable there)
            # rotation keeps max_models_to_save checkpoints; a larger K can
            # never be satisfied and would silently ensemble fewer members
            raise ValueError(
                f"test_ensemble_top_k ({self.test_ensemble_top_k}) cannot "
                f"exceed max_models_to_save ({self.max_models_to_save})"
            )
        if self.strategy not in TRAIN_STRATEGIES:
            hint = (
                " ('protonet' is forward-only — a serving tier, not a "
                "trainable objective; put it in serving.strategies)"
                if self.strategy == "protonet"
                else ""
            )
            raise ValueError(
                f"strategy must be one of {list(TRAIN_STRATEGIES)}{hint}, "
                f"got {self.strategy!r}"
            )
        if self.matmul_precision not in ("default", "high", "highest"):
            raise ValueError(
                f"matmul_precision must be 'default', 'high' or 'highest', "
                f"got {self.matmul_precision!r}"
            )
        if self.remat_policy not in REMAT_POLICIES:
            raise ValueError(
                f"remat_policy must be one of {sorted(REMAT_POLICIES)} "
                f"('' derives from remat_inner_steps), got {self.remat_policy!r}"
            )
        if self.train_steps_per_dispatch < 1:
            raise ValueError(
                f"train_steps_per_dispatch must be >= 1, "
                f"got {self.train_steps_per_dispatch}"
            )
        if self.checkpoint_shards < 0:
            raise ValueError(
                f"checkpoint_shards must be >= 0 (0 = auto), "
                f"got {self.checkpoint_shards}"
            )
        if self.parallel.tp_convs and not self.conv_via_patches:
            # tp_convs is meaningless (and partitioner-fatal) on the native
            # conv path; the patches-GEMM form is a strict requirement, so
            # enable it rather than bounce the config back
            self.conv_via_patches = True
        # direct Config(precision={...}) construction (bench A/B knobs) hands
        # the nested block over as a plain dict — same coercion the
        # resilience block does for its watchdog
        if isinstance(self.precision, dict):
            self.precision = PrecisionConfig(**self.precision)
        if isinstance(self.autoscale, dict):
            self.autoscale = AutoscaleConfig(**self.autoscale)
        if self.precision.fuse_conv_bn and not self.conv_via_patches:
            # the fused conv->BN epilogue IS a patches-GEMM epilogue; enable
            # the patches form rather than bounce the config back (the same
            # policy tp_convs gets above)
            self.conv_via_patches = True

    # --- episode shape (reference config.yaml:22-26) ---
    num_classes_per_set: int = 20
    num_samples_per_class: int = 5
    batch_size: int = 8
    num_of_gpus: int = 1  # kept for schema parity; superseded by parallel.dp

    # --- seeds (reference config.yaml:36-39) ---
    seed: int = 0
    train_seed: int = 0
    val_seed: int = 0
    test_seed: int = 0
    # reference quirk (data.py:143-148): the test episode stream is seeded from
    # val_seed, ignoring test_seed. True reproduces the reference.
    test_stream_uses_val_seed: bool = True

    # --- MAML++ core (reference config.yaml:41-56) ---
    # Adaptation strategy the meta-objective trains (core/strategies.py):
    #   "maml++"  the full second-order rollout — the default, bit-identical
    #             to the pre-registry path (same jaxpr, same program keys)
    #   "fomaml"  first-order MAML: stop-gradient on the inner grads, so the
    #             second-order terms vanish from the train program. By
    #             construction identical to maml++ with second_order=false.
    #   "anil"    head-only inner loop (Raghu et al.): the scan carries only
    #             the classifier-head fast weights — the inner backward and
    #             the meta-graph through it shrink to the head.
    # "protonet" is serving-only (ServingConfig.strategies): its adaptation
    # is a forward pass, there is no inner loop to meta-train here.
    strategy: str = "maml++"
    learnable_inner_opt_params: bool = True
    # Per-STEP learnable inner-opt hyperparams: original MAML++ LSLR learns a
    # separate lr per (tensor, inner step); the bamos fork regressed this to
    # per-tensor only (SURVEY.md §2.2 "per-tensor, not per-step"). False
    # reproduces the fork; True restores upstream LSLR (hparams gain a
    # leading [num_steps] axis; eval steps beyond the trained horizon reuse
    # the last step's values). Requires learnable_inner_opt_params.
    lslr_per_step: bool = False
    use_multi_step_loss_optimization: bool = True
    multi_step_loss_num_epochs: int = 10
    minimum_per_task_contribution: float = 0.01  # unused in reference; schema parity
    second_order: bool = True
    first_order_to_second_order_epoch: int = -1
    number_of_training_steps_per_iter: int = 5
    number_of_evaluation_steps_per_iter: int = 5

    # --- schedule (reference config.yaml:46-61) ---
    num_evaluation_tasks: int = 600
    total_epochs: int = 150
    total_epochs_before_pause: int = 150
    total_iter_per_epoch: int = 500
    continue_from_epoch: str = "latest"
    evaluate_on_test_set_only: bool = False
    # checkpoint rotation policy: "latest" keeps the most recent
    # max_models_to_save epoch files (reference-like), "best_val" keeps the
    # top ones by validation accuracy (upstream MAML++ kept best-5 for test
    # ensembling; SURVEY.md §2.9 item 4)
    checkpoint_rotation: str = "latest"
    # test-time ensembling: average softmax probabilities of the top-K
    # saved checkpoints by val accuracy (1 = best model only, the default;
    # upstream MAML++ ensembled its top 5)
    test_ensemble_top_k: int = 1
    meta_learning_rate: float = 0.001
    min_learning_rate: float = 1.0e-05

    # --- model / inner optim (reference config.yaml:67-85) ---
    net: str = "vgg"
    inner_optim: InnerOptimConfig = field(default_factory=InnerOptimConfig)
    # Reference deep-copies the outer Adam's per-param state into the inner
    # optimizer before each task's rollout (few_shot_learning_system.py:219-220,
    # with a one-task lag). We implement the *intent* — inner Adam moments seeded
    # from the outer optimizer's current state, no lag — and only for inner Adam
    # (the deepcopy would poison SGD/Rprop state dicts). SURVEY.md §2.2.
    warm_start_inner_opt_from_outer: bool = True

    # --- experiment dirs ---
    experiment_name: str = ""  # default: {dataset}.{n_way}.{k_shot}
    experiment_root: str = "exps"

    # --- TPU-native knobs (no reference equivalent) ---
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    # --- few-shot serving engine (serving/ package; no reference equivalent) ---
    serving: ServingConfig = field(default_factory=ServingConfig)
    # --- fault tolerance (resilience/ package; no reference equivalent) ---
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    # --- telemetry (observability/ package; no reference equivalent) ---
    observability: ObservabilityConfig = field(default_factory=ObservabilityConfig)
    # --- AOT prewarm (compile/ package; ROADMAP item 2) ---
    aot: AotConfig = field(default_factory=AotConfig)
    # --- mixed precision (ops/precision.py; ROADMAP item 3) ---
    precision: PrecisionConfig = field(default_factory=PrecisionConfig)
    # --- fleet autoscaling (serving/autoscaler.py; ISSUE 18). OFF by
    # default: the import-light supervisor reads these as fleet_serve.py
    # flags, never through this object — the block exists so run configs
    # can DOCUMENT their fleet policy next to the serving block. ---
    autoscale: AutoscaleConfig = field(default_factory=AutoscaleConfig)
    compute_dtype: str = "float32"  # or "bfloat16" for MXU-friendly compute
    remat_inner_steps: bool = True  # jax.checkpoint per inner step (SURVEY §5.7)
    # Rematerialization POLICY for the scanned inner step (core/maml.py
    # ``_adapt_loop`` and the MSL ``_rollout`` branch) — the graded dial
    # between the all-or-nothing extremes the boolean above offers:
    #   ""                    derive from remat_inner_steps (True -> "full",
    #                         False -> "none"): bit-identical legacy behavior
    #   "none"                no jax.checkpoint — save every intermediate
    #                         (fastest step, highest peak program bytes)
    #   "full"                jax.checkpoint with the default nothing_saveable
    #                         policy — recompute everything (the legacy True)
    #   "dots_saveable"       save dot/conv outputs, recompute the cheap
    #                         elementwise chain (usually the sweet spot: most
    #                         of the memory win at a fraction of full's
    #                         recompute+compile cost)
    #   "dots_with_no_batch_dims_saveable"
    #                         like dots_saveable but batched GEMMs (the
    #                         task-vmapped patches convs) are recomputed too
    # (jax's everything_saveable is deliberately rejected — see the
    # REMAT_POLICIES note: it changes the primal under grad on this jax.)
    # Each compiled program's argument/output/temp/peak bytes land in the
    # compile ledger (observability/compile_ledger.py ``memory`` column), so
    # every policy choice has a bytes-and-seconds price tag next to the HBM
    # watermarks. An explicit value here wins over remat_inner_steps.
    remat_policy: str = ""
    # Fully unroll the inner-step lax.scan: removes scan sequencing overhead
    # and lets XLA fuse across steps (~+10% meta-steps/s on v5e for the
    # flagship config); costs compile time O(steps). Remat still applies
    # per step, so memory stays O(1) in steps.
    unroll_inner_steps: bool = True
    # Route the inner SGD step through the fused Pallas kernel
    # (ops/pallas_update.py): one kernel over the packed param pytree per
    # inner step instead of one elementwise op per leaf. Identical math
    # (custom VJP; parity-tested). SGD/gd inner optimizer only.
    use_pallas_inner_update: bool = False
    # Strict recompile guard (utils/strictmode.py::RecompileGuard): declare
    # the compiled program families up front (train-step variants, serving
    # shape/batch buckets) and RAISE on any lowering outside them, instead
    # of silently eating an XLA compile mid-run. Off by default (oversize
    # serving requests legitimately compile exact shapes on demand); turn on
    # in tests and perf-sensitive deployments where an unplanned recompile
    # is a bug, not a convenience.
    strict_recompile_guard: bool = False
    profile_dir: str = ""  # non-empty: write jax.profiler traces here
    # Persistent XLA compilation cache directory (utils/compcache.py — the
    # one copy of the setup every entry point used to duplicate). Empty =
    # the JAX_COMPILATION_CACHE_DIR env var, else the shared default
    # ~/.cache/htymp_tpu_xla.
    compilation_cache_dir: str = ""
    # XLA matmul/conv precision for f32 operands. On TPU the "default" is a
    # single bfloat16 MXU pass (8-bit mantissa) even when tensors are f32 —
    # fine for forward inference, but the unrolled second-order meta-gradient
    # is a small residual of large terms and can drown in that rounding on
    # hard (large-n_way) tasks while easy tasks still train. "high" =
    # 3-pass bf16 (~f32 quality at ~2-3x matmul cost), "highest" = full f32
    # emulation (~6 passes). Applied process-wide by the entry point /
    # MAMLSystem via jax.config jax_default_matmul_precision.
    matmul_precision: str = "default"  # default | high | highest
    # Outer steps fused into one device dispatch (lax.scan over a stacked
    # [K]-batch chunk, core/maml.py::train_step_multi). Identical math to
    # K single dispatches; amortizes per-call host/RPC overhead — material
    # when the chip sits behind a network tunnel. 1 = one dispatch per step.
    # total_iter_per_epoch need not divide evenly: the remainder runs
    # through the single-step path.
    train_steps_per_dispatch: int = 1
    # Scan the whole fixed evaluation set inside one device call
    # (core/maml.py::eval_step_multi) instead of one dispatch per eval
    # batch. Same math; off by default so parity runs keep the
    # on-chip-validated per-batch eval program (single-host only — the
    # multi-host eval path gathers per batch).
    eval_fused_dispatch: bool = False
    # Donate the TrainState buffers to the compiled train step (halves HBM
    # for the state and lets XLA update in place). Donation must be a pure
    # memory optimization, but on the attached TPU's PJRT plugin it is NOT:
    # the round-4 A/B probe (scripts/donation_probe.py, 40 streamed steps,
    # 20w5s b8) measured per-step losses diverging from the no-donate arm at
    # step 0 and final params off by up to 32% rel
    # (results/r4/diag_chain.log, verdict DONATION-CORRUPTION) — the
    # corruption signature behind the 20-way on-chip training collapse
    # (results/r4/DIAG_20way_r4.md). Donation is ignored on CPU, which is
    # why every CPU probe was healthy. Default OFF: these models' train
    # state is ~.5 MB, so donation buys nothing here; turn on only on a
    # platform whose aliasing you have verified with the probe.
    donate_train_state: bool = False
    # Donate the per-step episode batch buffers (the [B, n_way, k, H, W, C]
    # support/target tensors) to the compiled train step. Unlike the train
    # state, the batch is throwaway BY CONSTRUCTION — the loader transfers a
    # fresh one every step and nothing ever reads a batch after its dispatch
    # — so this is safe independent of the donate_train_state corruption
    # verdict above (that bug is the state buffer being read back while
    # aliased; a batch has no read-back). Cuts the batch's bytes out of the
    # program's peak (visible as ``alias`` bytes in the ledger's memory
    # column). Off by default: bit-identical to pre-donation behavior.
    donate_batch: bool = False
    # Runtime aliasing self-check gating donate_train_state (the
    # scripts/donation_probe.py verdict productized,
    # observability/donation.py::donation_selfcheck): before the first real
    # step, run a tiny in-process A/B — donate vs no-donate arms over the
    # same streamed batches — and REFUSE donation (fall back to no-donate,
    # loudly, with a donation_refused event) when the arms diverge. The
    # TPU-plugin corruption class (results/r4 DONATION-CORRUPTION) can then
    # never silently recur. Only consulted when donate_train_state is on.
    donation_selfcheck: bool = True
    # Force the lax.reduce_window max-pool path (select_and_scatter backward
    # == torch's first-argmax tie subgradient) instead of the faster
    # reshape+max path (even-split tie subgradient). The conventions differ
    # only on tied window maxima — measure-zero in f32 but plausible under
    # bfloat16 quantization — so this is the escape hatch for ruling the
    # pooling convention in/out during on-chip mixed-precision parity
    # debugging (see models/layers.py max_pool docstring, PARITY.md).
    max_pool_reduce_window: bool = False
    # Express every conv as patch-extraction + dot_general (implicit GEMM
    # made explicit; same math up to accumulation order). The enabler for
    # parallel.tp_convs — see models/layers.py conv2d ``via_patches`` — and
    # auto-enabled by it; usable standalone for A/B perf or numerics probes.
    conv_via_patches: bool = False
    # --- elastic recovery (ISSUE 6; experiment/runner.py + checkpoint.py) ---
    # Async checkpointing: epoch saves run on a background writer thread
    # with a one-save lag (the runner blocks only on the PREVIOUS save at
    # the next save point), so serialization never sits on the step path.
    # Auto-disabled when donate_train_state is on (donation invalidates the
    # buffers a lagged writer would still be reading).
    checkpoint_async: bool = True
    # Checkpoint format-3 sharding: split each epoch checkpoint across N
    # per-shard files + a checksummed manifest (the commit point), so dp x mp
    # saves stop funneling through one host-side blob. 0 = auto (one shard
    # per mesh device, i.e. dp*mp; single-device runs keep the format-2
    # blob); 1 = force single-blob; N>=2 = force N shards.
    checkpoint_shards: int = 0
    # Mesh grow-back: when the run is on a degraded mesh (device loss,
    # resume on a shrunken slice), probe the visible device count at every
    # epoch boundary and grow the mesh back toward the requested dp x mp as
    # devices return — resharding the live TrainState up, the inverse of
    # degraded_mesh_plan (parallel/mesh.py::grow_mesh_plan). Costs one
    # device-count probe per epoch while degraded; nothing when healthy.
    elastic_grow: bool = True
    # Early divergence abort (sweep-time guard; 0.0 disables): exit with
    # code 3 when train accuracy is still below this after
    # ``early_abort_epoch`` epochs — a collapsing run (the on-chip 20-way
    # failure mode) should release the chip instead of burning its full
    # budget. scripts/sweep.sh treats rc=3 as permanent, not retryable.
    early_abort_train_acc: float = 0.0
    early_abort_epoch: int = 3

    # ------------------------------------------------------------------
    @property
    def image_shape(self):
        """(H, W, C) from the dataset registry (reference
        few_shot_learning_system.py:41-46 hard-codes the same table)."""
        from .data.registry import get_dataset_spec  # local: avoid import cycle

        return get_dataset_spec(self.dataset.name).image_shape

    @property
    def is_imagenet(self) -> bool:
        return "imagenet" in self.dataset.name

    @property
    def resolved_remat_policy(self) -> str:
        """The effective inner-step remat policy: an explicit
        ``remat_policy`` wins; empty derives from the legacy boolean
        (``remat_inner_steps=True`` -> "full", False -> "none") so every
        pre-policy config traces the exact same program it always did."""
        if self.remat_policy:
            return self.remat_policy
        return "full" if self.remat_inner_steps else "none"

    @property
    def effective_sets_are_pre_split(self) -> bool:
        """Resolve the None='auto by dataset' default at the USE site, so the
        stored config keeps None and re-targeting a saved config to another
        dataset re-derives the right split mode."""
        if self.sets_are_pre_split is None:
            return self.is_imagenet
        return self.sets_are_pre_split

    def run_name(self) -> str:
        # reference hydra run-dir naming: {dataset}.{n_way}.{k_shot}.local
        # (config.yaml:2-4)
        if self.experiment_name:
            return self.experiment_name
        return f"{self.dataset.name}.{self.num_classes_per_set}.{self.num_samples_per_class}"

    def run_dir(self) -> str:
        return os.path.join(self.experiment_root, self.run_name())

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# Loading: YAML + dotlist overrides
# ---------------------------------------------------------------------------


def _coerce(value: str) -> Any:
    try:
        return json.loads(value)
    except (json.JSONDecodeError, ValueError):
        return value


def _set_dotted(data: Dict[str, Any], dotted: str, value: Any) -> None:
    keys = dotted.split(".")
    node = data
    for name in keys[:-1]:
        child = node.setdefault(name, {})
        if not isinstance(child, dict):
            # the base value may be a preset name (e.g. `inner_optim: gd` in
            # YAML followed by a CLI `inner_optim.lr=0.05`): expand the preset
            # to its dict form so the dotted override can land on top of it.
            presets = {"dataset": DATASET_PRESETS, "inner_optim": INNER_OPTIM_PRESETS}.get(name)
            if presets is None or not isinstance(child, str) or child not in presets:
                raise KeyError(
                    f"cannot apply override {dotted!r}: {name!r} is the "
                    f"non-mapping value {child!r}"
                )
            child = dataclasses.asdict(presets[child])
            node[name] = child
        node = child
    node[keys[-1]] = value


def _merge(base: Dict[str, Any], override: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(base)
    for k, v in override.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _merge(out[k], v)
        else:
            out[k] = v
    return out


def _dataclass_from_dict(cls, data: Dict[str, Any]):
    kwargs = {}
    fields = {f.name: f for f in dataclasses.fields(cls)}
    unknown = set(data) - set(fields)
    if unknown:
        raise KeyError(f"unknown config keys for {cls.__name__}: {sorted(unknown)}")
    for name, f in fields.items():
        if name not in data:
            continue
        value = data[name]
        if name in ("dataset", "inner_optim", "parallel", "serving", "resilience", "observability", "aot", "precision", "autoscale"):
            sub_cls = {"dataset": DatasetConfig, "inner_optim": InnerOptimConfig, "parallel": ParallelConfig, "serving": ServingConfig, "resilience": ResilienceConfig, "observability": ObservabilityConfig, "aot": AotConfig, "precision": PrecisionConfig, "autoscale": AutoscaleConfig}[name]
            presets = {"dataset": DATASET_PRESETS, "inner_optim": INNER_OPTIM_PRESETS}.get(name, {})
            if isinstance(value, str):
                if value not in presets:
                    raise KeyError(f"unknown {name} preset {value!r}; have {sorted(presets)}")
                value = dataclasses.replace(presets[value])
            elif isinstance(value, dict):
                value = _dataclass_from_dict(sub_cls, value)
        kwargs[name] = value
    return cls(**kwargs)


def load_config(
    yaml_path: Optional[str] = None,
    overrides: Optional[List[str]] = None,
) -> Config:
    """Build a Config from an optional YAML file and ``key=value`` overrides.

    Overrides use dotted paths (``inner_optim.lr=0.05``); preset names can be
    given for ``dataset=`` / ``inner_optim=`` (e.g. ``inner_optim=adam``),
    mirroring the reference's ``inner_optim: ${gd}`` node interpolation.
    """
    data: Dict[str, Any] = {}
    if yaml_path:
        with open(yaml_path) as f:
            loaded = yaml.safe_load(f) or {}
        data = _merge(data, loaded)
    for item in overrides or []:
        if "=" not in item:
            raise ValueError(f"override {item!r} is not key=value")
        key, _, raw = item.partition("=")
        _set_dotted(data, key.strip(), _coerce(raw.strip()))
    return _dataclass_from_dict(Config, data)


def save_config(cfg: Config, path: str) -> None:
    with open(path, "w") as f:
        yaml.safe_dump(cfg.to_dict(), f, sort_keys=False)
