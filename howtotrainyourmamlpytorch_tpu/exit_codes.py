"""Single source of truth for the process exit-code contract.

Every harness decision in this repo keys off a small set of process exit
codes (``scripts/sweep.sh`` restart policy, the chaos-campaign invariants,
the driver's rc classification) plus two serving-side HTTP degradation
statuses. Before this module they were scattered as bare literals across
five files and one markdown table, each free to drift; now the literals
live HERE and everything else imports them. The ``graftlint`` contract
rules enforce it statically: GL301 flags any bare registry literal at an
exit site, and GL302 cross-checks the ``docs/OPERATIONS.md`` rc table
against :data:`TRAIN_PROCESS_RCS`.

Deliberately dependency-free (no jax, no package-relative imports): scripts
that must stay import-light before the backend is known-up
(``scripts/wait_for_tpu.py``, ``bench.py``) load this module directly by
file path instead of importing the (heavy) package.
"""

# graftlint: import-light — file-path-loaded before the backend is known-up
# (GL213 gates the closure)

# --- generic CLI codes ----------------------------------------------------
#: completed / all invariants held
OK = 0
#: generic CLI usage / structured-failure code (argparse convention)
USAGE = 2

# --- training-process codes (the sweep.sh restart policy) -----------------
#: permanent divergence: NaN-rollback ladder exhausted or early-abort
#: tripped. Retrying resumes the same collapsing trajectory — do NOT retry.
DIVERGED = 3
#: preemption (SIGTERM/SIGINT): emergency checkpoint with a mid-epoch
#: cursor was written; restart resumes exactly (EX_TEMPFAIL).
PREEMPTED = 75
#: wedge watchdog: zero progress past the deadline; thread stacks are in
#: logs/events.jsonl and an emergency checkpoint from the last settled
#: state was written. Restart free, but gate on the tunnel first.
WEDGED = 76
#: legacy: an *outer* ``timeout`` killed a hung process that had no
#: watchdog. Documented so old logs stay readable; should no longer occur.
LEGACY_TIMEOUT_KILL = 124

# --- TPU wait-gate codes (scripts/wait_for_tpu.py) ------------------------
#: the backend never came up inside --deadline-s (mixed probe failures)
TPU_WAIT_DEADLINE = 64
#: K consecutive probes hung — the dead-tunnel signature; gave up early
TPU_WAIT_WEDGED = 65

# --- serving drain codes (serving/server.py, scripts/serve.py) ------------
#: graceful drain (SIGTERM to a serving process) could not complete inside
#: serving.drain_deadline_s: in-flight/queued work was still pending when
#: the deadline expired. Hot sessions were still spilled and logs closed,
#: but a request may have been dropped — the supervisor should treat the
#: replica's last seconds as lossy. A clean drain exits 0.
DRAIN_DEADLINE = 77

# --- serving HTTP degradation codes (serving/server.py) -------------------
#: router admission control: the session's affine replica is at its
#: admission bound — shed BEFORE queueing, sent with Retry-After
HTTP_TOO_MANY_REQUESTS = 429
#: load shed (queue full) or circuit breaker open — sent with Retry-After
HTTP_UNAVAILABLE = 503
#: one request ran past resilience.request_deadline_s
HTTP_DEADLINE = 504

# --- derived sets ---------------------------------------------------------
#: what a training process may legitimately exit with (the chaos-campaign
#: rc-discipline invariant; anything else is an undocumented failure mode)
DOCUMENTED_RCS = (OK, DIVERGED, PREEMPTED, WEDGED)
#: restart-not-fail codes: both are backed by an emergency checkpoint and
#: sweep.sh relaunches them without burning a watchdog attempt
RESTARTABLE_RCS = (PREEMPTED, WEDGED)

#: the docs/OPERATIONS.md "Exit-code table" rows, one meaning per code —
#: GL302 asserts the markdown table and this dict never drift
TRAIN_PROCESS_RCS = {
    OK: "completed",
    DIVERGED: "permanent divergence (NaN ladder exhausted / early abort)",
    PREEMPTED: "preemption: emergency checkpoint + mid-epoch cursor",
    WEDGED: "wedged: watchdog saw zero progress past the deadline",
    LEGACY_TIMEOUT_KILL: "legacy outer-timeout kill (pre-watchdog)",
}


def describe(rc: int) -> str:
    """Human label for a process exit code (unknown codes say so)."""
    if rc in TRAIN_PROCESS_RCS:
        return TRAIN_PROCESS_RCS[rc]
    if rc == TPU_WAIT_DEADLINE:
        return "TPU wait gate: deadline exceeded"
    if rc == TPU_WAIT_WEDGED:
        return "TPU wait gate: consecutive probes hung (dead tunnel)"
    if rc == DRAIN_DEADLINE:
        return "serving drain: deadline exceeded with work still in flight"
    if rc == USAGE:
        return "usage / structured failure"
    return f"undocumented exit code {rc}"
