"""Filesystem index bootstrap with the reference's on-disk JSON cache format.

On first run, walk the dataset directory, label each image by
"<grandparent>/<parent>" path components, verify each image opens, and cache
three JSONs *next to* the dataset dir (reference ``data.py:241-342``):
``{dataset}.json`` (class-idx -> filepath list), ``map_to_label_name_*.json``,
``label_name_to_map_*.json``. The formats match the reference's verified
on-disk artifacts so existing caches interoperate.

Deviations from the reference, on purpose:
- verification uses a thread pool (PIL decoding releases the GIL) instead of a
  4-process fork pool;
- a corrupt image is dropped with a warning instead of shelling out to
  ImageMagick ``convert`` (reference ``data.py:299``);
- the dataset-integrity count check fails fast instead of deleting the dataset
  dir and recursing forever (reference ``utils/dataset_tools.py:42-44`` — the
  re-download logic it relied on is commented out upstream).
"""

import concurrent.futures
import json
import os
import warnings
from typing import Dict, List, Optional, Tuple

from PIL import Image

from . import registry

_IMAGE_EXTS = (".jpeg", ".png", ".jpg")

# reference utils/dataset_tools.py:29-40 expected image counts
EXPECTED_COUNTS = {"omniglot_dataset": 1623 * 20, "mini_imagenet_full_size": 100 * 600}


def label_from_path(filepath: str, class_indexes=(-3, -2), labels_as_int=False):
    bits = filepath.split("/")
    label = "/".join(bits[idx] for idx in class_indexes)
    return int(label) if labels_as_int else label


def _verify_image(filepath: str):
    try:
        with Image.open(filepath) as im:
            im.verify()
        return filepath
    except Exception:
        warnings.warn(f"dropping unreadable image {filepath}")
        return None


def index_paths(data_path: str, dataset_name: str, cache_dir: Optional[str] = None) -> Tuple[str, str, str]:
    dataset_dir = cache_dir or os.path.split(os.path.normpath(data_path))[0]
    return (
        os.path.join(dataset_dir, f"{dataset_name}.json"),
        os.path.join(dataset_dir, f"map_to_label_name_{dataset_name}.json"),
        os.path.join(dataset_dir, f"label_name_to_map_{dataset_name}.json"),
    )


def _resolve_paths(paths: Dict, data_path: str) -> Dict:
    """Cached indexes may hold paths relative to the original repo root (the
    reference's shipped ``omniglot_dataset.json`` does). Resolve them against
    the dataset's enclosing repo dir when they don't exist as given."""
    root = os.path.dirname(os.path.split(os.path.normpath(data_path))[0])
    probe = next((p for v in paths.values() for p in v[:1]), None)
    if probe is None or os.path.exists(probe):
        return paths
    if os.path.exists(os.path.join(root, probe)):
        return {k: [os.path.join(root, p) for p in v] for k, v in paths.items()}
    return paths


def build_index(
    data_path: str,
    class_indexes=(-3, -2),
    labels_as_int: bool = False,
    verify: bool = True,
    max_workers: int = 8,
) -> Tuple[Dict[int, List[str]], Dict[int, str], Dict[str, int]]:
    files = []
    for subdir, _, names in os.walk(data_path):
        for name in names:
            if name.lower().endswith(_IMAGE_EXTS):
                files.append(os.path.abspath(os.path.join(subdir, name)))
    if verify:
        with concurrent.futures.ThreadPoolExecutor(max_workers=max_workers) as pool:
            files = [f for f in pool.map(_verify_image, files) if f is not None]
    labels = sorted({label_from_path(f, class_indexes, labels_as_int) for f in files})
    idx_to_label = {i: label for i, label in enumerate(labels)}
    label_to_idx = {label: i for i, label in enumerate(labels)}
    paths: Dict[int, List[str]] = {i: [] for i in idx_to_label}
    for f in sorted(files):
        paths[label_to_idx[label_from_path(f, class_indexes, labels_as_int)]].append(f)
    return paths, idx_to_label, label_to_idx


def load_or_build_index(
    data_path: str,
    dataset_name: str,
    class_indexes=(-3, -2),
    labels_as_int: bool = False,
    reset_stored_filepaths: bool = False,
    cache_dir: Optional[str] = None,
):
    """Load the JSON caches, building them on first run (reference
    ``load_datapaths``, ``data.py:241-276``). Returns
    (class_idx->paths with *string* keys as JSON round-trips them,
    idx->label, label->idx). ``cache_dir`` overrides where the JSONs live —
    needed when the dataset dir is on a read-only mount."""
    paths_file, idx_to_label_file, label_to_idx_file = index_paths(
        data_path, dataset_name, cache_dir
    )
    if reset_stored_filepaths and os.path.exists(paths_file):
        os.remove(paths_file)
    try:
        with open(paths_file) as f:
            paths = json.load(f)
        with open(idx_to_label_file) as f:
            idx_to_label = json.load(f)
        with open(label_to_idx_file) as f:
            label_to_idx = json.load(f)
        return _resolve_paths(paths, data_path), idx_to_label, label_to_idx
    except (FileNotFoundError, json.JSONDecodeError):
        pass
    paths, idx_to_label, label_to_idx = build_index(data_path, class_indexes, labels_as_int)
    if cache_dir:
        os.makedirs(cache_dir, exist_ok=True)
    for obj, fname in [
        (paths, paths_file),
        (idx_to_label, idx_to_label_file),
        (label_to_idx, label_to_idx_file),
    ]:
        with open(fname, "w") as f:
            json.dump(obj, f)
    # re-load so key types match the cached-file case (JSON stringifies ints)
    return load_or_build_index(
        data_path, dataset_name, class_indexes, labels_as_int, cache_dir=cache_dir
    )


def check_dataset_integrity(data_path: str, dataset_name: str) -> int:
    """Count images and validate against the expected totals (reference
    ``utils/dataset_tools.py:29-40``) — fail fast on mismatch rather than the
    reference's delete-and-recurse loop. The pkl-packed mini-imagenet variant
    (reference accepts exactly 3 ``.pkl`` files, dataset_tools.py:37-40) is
    validated by its pickle count."""
    if not os.path.exists(data_path):
        raise FileNotFoundError(f"dataset dir missing: {data_path}")
    if registry.is_pkl_variant(dataset_name):
        total = sum(
            1
            for _, _, names in os.walk(data_path)
            for n in names
            if n.lower().endswith(".pkl")
        )
        if total != 3:
            raise RuntimeError(
                f"{dataset_name}: found {total} .pkl files, expected 3 "
                "(train/val/test pickles); dataset appears incomplete"
            )
        return total
    total = 0
    for _, _, names in os.walk(data_path):
        total += sum(1 for n in names if n.lower().endswith(_IMAGE_EXTS))
    expected = EXPECTED_COUNTS.get(dataset_name)
    if expected is not None and total != expected:
        raise RuntimeError(
            f"{dataset_name}: found {total} images, expected {expected}; "
            "dataset appears corrupt or incomplete"
        )
    return total
