"""Mixed-precision policy + batched patches-GEMM tests (ISSUE 9).

Three contracts pinned here:

1. **Off means off**: the default config resolves to the f32 policy whose
   cast helpers are the identity and whose traced programs contain no bf16 —
   together with the rest of the suite's numeric pins (torch parity, eval
   parity, serving parity), that is the bit-identity evidence for
   ``Config.precision`` disabled.
2. **bf16 inner loop is validated, not assumed**: the tier-1 promotion of
   ``scripts/grad_precision_probe.py`` — meta-gradient cosine vs f32 within
   documented tolerances (>= 0.99 per tensor with non-negligible reference
   norm, >= 0.995 globally; conv-bias gradients are exactly zero under
   transductive BN, so their bf16/f32 'gradients' are pure roundoff noise
   and are excluded by the norm filter), plus a short-training accuracy
   parity check.
3. **The batched patches-GEMM and the fused conv->BN epilogue are the same
   math**: logits parity vs the per-sample/native path across stride/padding
   (train AND eval modes, weighted and not), and the vmapped program carries
   exactly ONE dot_general per conv layer — the "one fat GEMM" structure the
   restructure exists for.
"""

import dataclasses
import importlib.util
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from howtotrainyourmamlpytorch_tpu.config import (  # noqa: E402
    Config,
    PrecisionConfig,
    ServingConfig,
    load_config,
)
from howtotrainyourmamlpytorch_tpu.core import MAMLSystem  # noqa: E402
from howtotrainyourmamlpytorch_tpu.data.synthetic import synthetic_batch  # noqa: E402
from howtotrainyourmamlpytorch_tpu.models import build_vgg, layers  # noqa: E402
from howtotrainyourmamlpytorch_tpu.ops import precision as prec  # noqa: E402

from .test_maml_core import TINY_SHAPE, tiny_config  # noqa: E402

# ---------------------------------------------------------------------------
# helpers / fixtures
# ---------------------------------------------------------------------------


def _tiny_vgg(cfg):
    return build_vgg(
        TINY_SHAPE,
        cfg.num_classes_per_set,
        num_stages=2,
        cnn_num_filters=4,
        conv_via_patches=cfg.conv_via_patches,
        fuse_conv_bn=cfg.precision.fuse_conv_bn,
    )


def _system(**overrides):
    cfg = tiny_config(**overrides)
    return cfg, MAMLSystem(cfg, model=_tiny_vgg(cfg))


def _batch(seed=0):
    return {
        k: jnp.asarray(v)
        for k, v in synthetic_batch(2, 3, 2, 2, TINY_SHAPE, seed=seed).items()
    }


def _meta_grads(system, state, batch):
    tr = {"params": state.params, "hparams": state.inner_hparams}

    def obj(t):
        loss, _ = system._meta_objective(
            t, state.bn_state, state.opt_state, batch, 0, True,
            system.cfg.number_of_training_steps_per_iter, True,
        )
        return loss

    return jax.jit(jax.grad(obj))(tr)


@pytest.fixture(scope="module")
def arms():
    """One f32 and one bf16_inner system over the SAME tiny vgg shape/seed
    (masters initialize identically — init is f32 in both arms)."""
    _, f32 = _system()
    _, bf16 = _system(precision=PrecisionConfig(enabled=True))
    return f32, bf16


# ---------------------------------------------------------------------------
# 1. off-by-default bit-identity evidence
# ---------------------------------------------------------------------------


def test_default_policy_is_f32_identity():
    cfg, system = _system()
    assert system.precision.name == "f32"
    tree = {"w": jnp.ones((3, 3)), "b": jnp.zeros((3,))}
    # identity, not a copy: the f32 policy adds ZERO ops to the traced program
    assert system.precision.cast_fast_weights(tree) is tree
    p, x = system.precision.cast_forward_inputs(tree, tree["w"])
    assert p is tree and x is tree["w"]
    params, bn_state = system.model.init(jax.random.PRNGKey(0))
    jaxpr = jax.make_jaxpr(
        lambda p, s, xx: system._apply_forward(p, s, xx)
    )(params, bn_state, jnp.ones((4,) + TINY_SHAPE))
    assert "bf16" not in str(jaxpr)


def test_legacy_compute_dtype_keeps_per_forward_cast():
    """compute_dtype="bfloat16" WITHOUT the precision block stays the exact
    pre-policy behavior: per-forward operand casts, no rollout-entry cast,
    statistics in the compute dtype."""
    cfg, system = _system(compute_dtype="bfloat16")
    assert system.precision.name == "legacy_bf16"
    assert system.precision.stat_dtype is None
    tree = {"w": jnp.ones((3, 3))}
    assert system.precision.cast_fast_weights(tree) is tree  # no entry cast
    params, bn_state = system.model.init(jax.random.PRNGKey(0))
    jaxpr = str(
        jax.make_jaxpr(lambda p, s, xx: system._apply_forward(p, s, xx))(
            params, bn_state, jnp.ones((4,) + TINY_SHAPE)
        )
    )
    assert "bf16" in jaxpr  # the forward really runs in bf16


def test_precision_config_validation_and_roundtrip(tmp_path):
    with pytest.raises(ValueError):
        PrecisionConfig(compute_dtype="float16")
    with pytest.raises(ValueError):
        PrecisionConfig(stat_dtype="bfloat16")
    cfg = load_config(
        None, ["precision.enabled=true", "precision.fuse_conv_bn=true"]
    )
    assert cfg.precision.enabled and cfg.precision.fuse_conv_bn
    # the fused epilogue IS a patches epilogue: auto-enabled like tp_convs
    assert cfg.conv_via_patches
    from howtotrainyourmamlpytorch_tpu.config import save_config

    path = tmp_path / "cfg.yaml"
    save_config(cfg, str(path))
    again = load_config(str(path))
    assert again.precision == cfg.precision
    # Config(precision={...}) dict coercion (the bench.py A/B knob path)
    assert Config(precision={"enabled": True}).precision.enabled


# ---------------------------------------------------------------------------
# 2. bf16 inner loop: promoted grad-precision probe + training parity
# ---------------------------------------------------------------------------


def test_bf16_policy_resolves_and_masters_stay_f32(arms):
    _, bf16 = arms
    pol = bf16.precision
    assert pol.name == "bf16_inner" and pol.cast_inner
    assert pol.compute_dtype == jnp.bfloat16 and pol.stat_dtype == jnp.float32
    state = bf16.init_train_state()
    # masters: every float leaf of the TrainState stays f32
    for leaf in jax.tree.leaves((state.params, state.inner_hparams)):
        assert leaf.dtype == jnp.float32
    # fast weights come out of the rollout in the compute dtype
    fw = bf16.adapt_fast_weights(
        state,
        jnp.zeros((6,) + TINY_SHAPE),
        jnp.zeros((6,), jnp.int32),
        num_steps=1,
    )
    assert all(l.dtype == jnp.bfloat16 for l in jax.tree.leaves(fw))


def test_bf16_meta_grad_cosine_vs_f32(arms):
    """The tier-1 promotion of scripts/grad_precision_probe.py: the bf16
    inner loop's second-order meta-gradient must agree with f32 to the
    documented tolerances (per-tensor cosine >= 0.99 where the reference
    gradient is non-negligible; global cosine >= 0.995). Conv-bias tensors
    are excluded by the norm filter: under transductive BN a conv bias
    cancels exactly, so both arms' 'gradients' there are roundoff noise."""
    f32, bf16 = arms
    batch = _batch(0)
    ga = _meta_grads(f32, f32.init_train_state(), batch)
    gb = _meta_grads(bf16, bf16.init_train_state(), batch)
    flat_a = jax.tree_util.tree_flatten_with_path(ga)[0]
    flat_b = jax.tree.leaves(gb)
    norms = [np.linalg.norm(np.asarray(l, np.float64)) for _, l in flat_a]
    floor = max(norms) * 1e-5
    checked = 0
    all_a, all_b = [], []
    for (path, la), lb, norm in zip(flat_a, flat_b, norms):
        a = np.asarray(la, np.float64).ravel()
        b = np.asarray(lb, np.float64).ravel()
        all_a.append(a)
        all_b.append(b)
        if norm < floor:
            continue  # exact-zero gradient: noise in both arms
        checked += 1
        cos = a @ b / (np.linalg.norm(a) * np.linalg.norm(b))
        assert cos >= 0.99, f"{jax.tree_util.keystr(path)}: cosine {cos:.4f}"
    assert checked >= 12  # the filter must not hollow the test out
    a, b = np.concatenate(all_a), np.concatenate(all_b)
    global_cos = a @ b / (np.linalg.norm(a) * np.linalg.norm(b))
    assert global_cos >= 0.995, f"global cosine {global_cos:.5f}"


def test_bf16_short_training_accuracy_parity(arms):
    """Post-training val-accuracy delta vs f32 within the documented toy
    tolerance (|delta| <= 0.25 at this scale — two 6-step runs on a 4-filter
    net), and the bf16 arm's losses stay finite while masters stay f32."""
    f32, bf16 = arms
    results = {}
    for name, system in (("f32", f32), ("bf16", bf16)):
        state = system.init_train_state()
        losses = []
        for i in range(6):
            state, out = system.train_step(state, _batch(i), epoch=0)
            losses.append(float(out.loss))
        ev = system.eval_step(state, _batch(99))
        results[name] = (losses, float(ev.accuracy))
        assert all(np.isfinite(l) for l in losses), (name, losses)
        for leaf in jax.tree.leaves(state.params):
            assert leaf.dtype == jnp.float32
    delta = abs(results["f32"][1] - results["bf16"][1])
    assert delta <= 0.25, results


def test_serving_engine_shares_the_policy(arms):
    """Train and serve share ONE policy: an engine over the bf16 system
    adapts in bf16 (bf16 cached fast weights) and reports the policy name
    through compile_counts -> /metrics."""
    from howtotrainyourmamlpytorch_tpu.serving import AdaptationEngine

    _, bf16 = arms
    serving = ServingConfig(
        support_buckets=[6], query_buckets=[4], max_batch_size=2
    )
    engine = AdaptationEngine(
        bf16, bf16.init_train_state(), serving_cfg=serving
    )
    assert engine.compile_counts()["precision"] == "bf16_inner"
    b = synthetic_batch(1, 3, 2, 2, TINY_SHAPE, seed=5)
    fw = engine.adapt(b["x_support"][0], b["y_support"][0])
    assert all(l.dtype == jnp.bfloat16 for l in jax.tree.leaves(fw))
    probs = engine.predict(fw, b["x_target"][0].reshape((-1,) + TINY_SHAPE)[:4])
    assert probs.dtype == np.float32  # the exit boundary is f32
    np.testing.assert_allclose(probs.sum(axis=-1), 1.0, rtol=1e-3)


# ---------------------------------------------------------------------------
# 3. batched patches-GEMM + fused conv->BN epilogue parity
# ---------------------------------------------------------------------------


def test_vmapped_patches_conv_is_one_batched_gemm():
    """The restructure's point, pinned structurally: per-task kernels under
    vmap collapse into ONE dot_general (a single batched GEMM) per conv —
    and the logits match the vmapped native conv."""
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    ws = {"w": jax.random.normal(k1, (3, 3, 3, 4, 8)) * 0.1}  # [tasks, ...]
    xs = jax.random.normal(k2, (3, 5, 9, 9, 4))  # [tasks, samples, ...]

    def per_task(w, x, via):
        return layers.conv2d({"w": w}, x, stride=1, padding=1, via_patches=via)

    patched = jax.vmap(lambda w, x: per_task(w, x, True))
    native = jax.vmap(lambda w, x: per_task(w, x, False))
    jaxpr = str(jax.make_jaxpr(patched)(ws["w"], xs))
    assert jaxpr.count("dot_general") == 1
    np.testing.assert_allclose(
        np.asarray(patched(ws["w"], xs)),
        np.asarray(native(ws["w"], xs)),
        rtol=1e-5,
        atol=1e-5,
    )


@pytest.mark.parametrize("stride,pad", [(1, 1), (2, 1), (1, 0)])
def test_fused_conv_bn_matches_separate_train_mode(stride, pad):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 7, 7, 3).astype(np.float32))
    conv_p = layers.init_conv(jax.random.PRNGKey(1), 3, 3, 3, 6, bias=True)
    bn_p = {
        "scale": jnp.asarray(rng.rand(6).astype(np.float32) + 0.5),
        "bias": jnp.asarray(rng.randn(6).astype(np.float32)),
    }
    _, bn_s = layers.init_batch_norm(6)
    for sample_weight in (None, jnp.asarray([1.0, 1.0, 1.0, 0.0])):
        ref = layers.conv2d_patches(conv_p, x, stride=stride, padding=pad)
        ref, ref_state = layers.batch_norm(
            bn_p, bn_s, ref, True, True, sample_weight=sample_weight
        )
        out, out_state = layers.conv2d_bn_patches(
            conv_p, bn_p, bn_s, x, stride=stride, padding=pad,
            use_batch_stats=True, update_running=True,
            sample_weight=sample_weight,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            ),
            out_state,
            ref_state,
        )


def test_fused_conv_bn_matches_separate_eval_mode():
    """use_batch_stats=False consults the running state — the mode where the
    conv bias must NOT silently vanish (it cancels only under batch stats)."""
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(3, 6, 6, 2).astype(np.float32))
    conv_p = layers.init_conv(jax.random.PRNGKey(3), 3, 3, 2, 5, bias=True)
    bn_p = {
        "scale": jnp.asarray(rng.rand(5).astype(np.float32) + 0.5),
        "bias": jnp.asarray(rng.randn(5).astype(np.float32)),
    }
    bn_s = {
        "mean": jnp.asarray(rng.randn(5).astype(np.float32)),
        "var": jnp.asarray(rng.rand(5).astype(np.float32) + 0.5),
        "count": jnp.asarray(3.0),
    }
    ref = layers.conv2d_patches(conv_p, x, stride=1, padding=1)
    ref, _ = layers.batch_norm(bn_p, bn_s, ref, use_batch_stats=False)
    out, out_state = layers.conv2d_bn_patches(
        conv_p, bn_p, bn_s, x, stride=1, padding=1, use_batch_stats=False
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)
    assert out_state is bn_s  # eval mode never touches the running state


def test_fused_conv_bn_stat_dtype_keeps_compute_dtype():
    """bf16 activations + f32 statistics: output stays bf16, fused and
    separate paths agree to bf16 tolerance."""
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(4, 6, 6, 2).astype(np.float32)).astype(jnp.bfloat16)
    conv_p = jax.tree.map(
        lambda a: a.astype(jnp.bfloat16),
        layers.init_conv(jax.random.PRNGKey(5), 3, 3, 2, 4, bias=False),
    )
    bn_p = {
        "scale": jnp.ones((4,), jnp.bfloat16),
        "bias": jnp.zeros((4,), jnp.bfloat16),
    }
    _, bn_s = layers.init_batch_norm(4)
    out, _ = layers.conv2d_bn_patches(
        conv_p, bn_p, bn_s, x, stride=1, padding=1, stat_dtype=jnp.float32
    )
    assert out.dtype == jnp.bfloat16
    ref = layers.conv2d_patches(conv_p, x, stride=1, padding=1)
    ref, _ = layers.batch_norm(bn_p, bn_s, ref, stat_dtype=jnp.float32)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=0.05, atol=0.05,
    )


def test_vgg_fused_model_matches_unfused():
    """Whole-model contract: a fuse_conv_bn build produces the same logits
    as the separate conv->BN build from identical init (train-mode apply,
    f32 — reassociation-level tolerance)."""
    kwargs = dict(num_stages=2, cnn_num_filters=4, conv_via_patches=True)
    plain = build_vgg(TINY_SHAPE, 3, **kwargs)
    fused = build_vgg(TINY_SHAPE, 3, fuse_conv_bn=True, **kwargs)
    params, state = plain.init(jax.random.PRNGKey(7))
    x = jax.random.normal(jax.random.PRNGKey(8), (5,) + TINY_SHAPE)
    la, _ = plain.apply(params, state, x)
    lb, _ = fused.apply(params, state, x)
    np.testing.assert_allclose(np.asarray(la), np.asarray(lb), rtol=1e-4, atol=1e-5)


def test_fused_conv_bn_gradients_match_separate():
    """The fused epilogue's BACKWARD matches the separate conv->BN path —
    the refactored normalize (g*a + shift) must carry the same gradients
    w.r.t. the conv kernel, the BN scale/shift, and the input, or the
    fusion would silently bend the meta-gradient. Eager layer-level check
    (no extra compiled programs; whole-model composition is covered by the
    sealed-guard drill below, which trains through the fused build)."""
    rng = np.random.RandomState(6)
    x = jnp.asarray(rng.randn(4, 6, 6, 3).astype(np.float32))
    conv_p = layers.init_conv(jax.random.PRNGKey(9), 3, 3, 3, 5, bias=True)
    bn_p = {
        "scale": jnp.asarray(rng.rand(5).astype(np.float32) + 0.5),
        "bias": jnp.asarray(rng.randn(5).astype(np.float32)),
    }
    _, bn_s = layers.init_batch_norm(5)

    def fused(cp, bp, xx):
        out, _ = layers.conv2d_bn_patches(cp, bp, bn_s, xx, stride=1, padding=1)
        return jnp.sum(jnp.tanh(out))

    def separate(cp, bp, xx):
        out = layers.conv2d_patches(cp, xx, stride=1, padding=1)
        out, _ = layers.batch_norm(bp, bn_s, out)
        return jnp.sum(jnp.tanh(out))

    ga = jax.grad(fused, argnums=(0, 1, 2))(conv_p, bn_p, x)
    gb = jax.grad(separate, argnums=(0, 1, 2))(conv_p, bn_p, x)
    # atol floor 1e-4: the conv-bias gradient is exactly zero under batch
    # stats (it cancels in the mean), so both paths produce only roundoff
    # noise there; real gradients are O(0.1-1) and still pinned by rtol
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
        ),
        ga,
        gb,
    )


# ---------------------------------------------------------------------------
# 4. prewarm / sealed-guard coverage of the new variants
# ---------------------------------------------------------------------------


def test_precision_programs_survive_sealed_guard_prewarm():
    """The acceptance drill at toy scale: with bf16 + fused GEMM on and the
    strict guard armed, AOT prewarm compiles the WHOLE planned family, the
    guard seals, and real train/eval dispatches run with ZERO
    outside-prewarm compiles."""
    cfg, system = _system(
        precision=PrecisionConfig(enabled=True, fuse_conv_bn=True),
        strict_recompile_guard=True,
        second_order=False,
        use_multi_step_loss_optimization=False,
    )
    state = system.init_train_state()
    summary = system.prewarm(state, max_workers=1)
    assert summary["programs"] == 4  # train/train_multi (F,F) + eval + eval_multi
    assert summary["errors"] == 0, summary
    assert system.recompile_guard.prewarmed
    state, out = system.train_step(state, _batch(0), epoch=0)
    system.eval_step(state, _batch(1))
    snap = system.recompile_guard.snapshot()
    assert snap["violations"] == []
    assert np.isfinite(float(out.loss))


# ---------------------------------------------------------------------------
# 5. bench knob + GSPMD probe contracts
# ---------------------------------------------------------------------------


def test_bench_precision_knob_mapping():
    import bench

    assert bench._precision_overrides("") == {"compute_dtype": "bfloat16"}
    assert bench._precision_overrides("legacy") == {"compute_dtype": "bfloat16"}
    assert bench._precision_overrides("f32") == {"compute_dtype": "float32"}
    bf = bench._precision_overrides("bf16")
    assert bf["precision"]["enabled"] is True
    with pytest.raises(ValueError):
        bench._precision_overrides("fp8")
    # the knob's dicts must build real configs with the intended policies
    assert prec.policy_from_config(
        Config(**bench._precision_overrides("bf16"))
    ).name == "bf16_inner"
    assert prec.policy_from_config(
        Config(**bench._precision_overrides("legacy"))
    ).name == "legacy_bf16"


def _load_gspmd_probe():
    spec = importlib.util.spec_from_file_location(
        "gspmd_conv_probe", os.path.join(REPO_ROOT, "scripts", "gspmd_conv_probe.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_gspmd_probe_verdict_contract():
    """The verdict line is the probe's whole interface: ok/crash/error map
    from the child's fate, schema stable, action always present."""
    probe = _load_gspmd_probe()
    ok = probe.verdict_from_child(0, True)
    crash = probe.verdict_from_child(-6, False)
    err = probe.verdict_from_child(3, False, "no second device")
    assert ok["verdict"] == "ok" and crash["verdict"] == "crash"
    assert err["verdict"] == "error" and "stderr_tail" in err
    for v in (ok, crash, err):
        assert {"probe", "verdict", "child_rc", "jax", "jaxlib", "action"} <= set(v)
        assert v["probe"] == "gspmd_native_conv"
    assert probe.verdict_from_child(134, False)["verdict"] == "crash"
    # a compile TIMEOUT must never masquerade as a crash verdict (it would
    # write a false 'still crashes' row into the OPERATIONS table)
    timeout = probe.verdict_from_child(-1, False, "timed out", timed_out=True)
    assert timeout["verdict"] == "error" and "stderr_tail" in timeout


@pytest.mark.slow
def test_gspmd_probe_e2e():
    """Full subprocess probe (jax import + compile in a child — slow tier).
    On this jaxlib the documented verdict is 'crash'; 'ok' is the signal to
    retire the patches detour (see OPERATIONS.md)."""
    probe = _load_gspmd_probe()
    report = probe.run_probe()
    assert report["verdict"] in ("ok", "crash"), report
