#!/bin/bash
# Round-3 accuracy part F — post-diagnosis queue. Usage:
#   scripts/run_accuracy_r3f.sh [extra override ...]
# Runs the remaining headline configs; pass the 20-way fix discovered by
# diag_chain (e.g. donate_train_state=false) as extra overrides, applied to
# every job. resnet-4 5w1s goes first (5-way family is proven stable, so it
# banks a third full-budget row even if the 20-way fix is wrong).
# DEADLINE_EPOCH (sweep.sh) gates job STARTS only — a job that begins just
# before the deadline still runs to completion, so set the deadline at
# least one full run-length before the chip must be free.
mkdir -p /root/repo/exps
EXTRA="$*"
exec "$(dirname "$0")/sweep.sh" \
  "omniglot.5.1.resnet-4.gd.s0 num_classes_per_set=5  num_samples_per_class=1 net=resnet-4 $EXTRA" \
  "omniglot.20.5.vgg.gd.s0     num_classes_per_set=20 num_samples_per_class=5 net=vgg $EXTRA" \
  "omniglot.20.1.vgg.gd.s0     num_classes_per_set=20 num_samples_per_class=1 net=vgg $EXTRA" \
  "omniglot.5.1.vgg.adam.s0    num_classes_per_set=5  num_samples_per_class=1 net=vgg inner_optim=adam $EXTRA"
