#!/bin/bash
# Round-3 accuracy matrix, part B: remaining configs after 5w1s completed
# (99.57% test) and 20w1s was parked for diagnosis (learned-lr runaway).
# Same watchdog-against-tunnel-wedge structure as run_accuracy_r3.sh.
set -u
cd /root/repo
COMMON="dataset=omniglot inner_optim=gd seed=0 train_seed=0 val_seed=0 \
 dataset.path=/root/reference/datasets/omniglot_dataset \
 index_cache_dir=/tmp/omniglot_idx load_into_memory=true \
 total_epochs=150 remat_inner_steps=false"
STALL_SECS=420
MAX_RESTARTS=8

run () {
  name=$1; shift
  out="exps/${name}.out"
  for attempt in $(seq 0 $MAX_RESTARTS); do
    echo "=== $(date -u +%H:%M:%S) start $name attempt=$attempt" >> exps/sweep_r3.log
    python train_maml_system.py $COMMON experiment_name="$name" "$@" \
      >> "$out" 2>&1 &
    pid=$!
    while kill -0 $pid 2>/dev/null; do
      sleep 30
      age=$(( $(date +%s) - $(stat -c %Y "$out") ))
      if [ "$age" -gt "$STALL_SECS" ]; then
        echo "=== $(date -u +%H:%M:%S) $name STALLED (log ${age}s old), killing $pid" >> exps/sweep_r3.log
        kill $pid 2>/dev/null; sleep 5; kill -9 $pid 2>/dev/null
        break
      fi
    done
    wait $pid; rc=$?
    echo "=== $(date -u +%H:%M:%S) $name attempt=$attempt rc=$rc" >> exps/sweep_r3.log
    [ $rc -eq 0 ] && return 0
    sleep 10
  done
  echo "=== $(date -u +%H:%M:%S) $name FAILED after $MAX_RESTARTS restarts" >> exps/sweep_r3.log
  return 1
}

run omniglot.5.5.vgg.gd.s0      num_classes_per_set=5  num_samples_per_class=5 net=vgg
run omniglot.5.1.resnet-4.gd.s0 num_classes_per_set=5  num_samples_per_class=1 net=resnet-4
run omniglot.20.5.vgg.gd.s0     num_classes_per_set=20 num_samples_per_class=5 net=vgg
echo "=== $(date -u +%H:%M:%S) PART B DONE" >> exps/sweep_r3.log
