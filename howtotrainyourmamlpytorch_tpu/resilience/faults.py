"""Deterministic, seeded fault injection at the framework's real seams.

The chip-wedge history (VERDICT.md round 5: a whole sweep lost to a wedged
backend with no graceful degradation anywhere) showed that the failure paths
are the least-tested code in the repo — because they could only be exercised
by real hardware misbehaving. This registry makes faults *drillable*: a
config/env-driven list of injection specs names a site (a seam the production
code already passes through), a fault kind, and a deterministic trigger, and
the seam fires the injector on every call. With no specs configured the
injector is inert — one attribute check per seam, no RNG draws, bit-identical
behavior to an unpatched build.

Sites wired in this codebase (grep for ``fire(`` / ``fire_bytes(``):

==================  ========================================================
``checkpoint.write``  ``experiment/checkpoint.py`` — the serialized blob
                      before the atomic write (corrupt-bytes = torn write,
                      raise = disk full, delay = slow NFS)
``checkpoint.read``   ``experiment/checkpoint.py`` — the blob after read,
                      before decode (corrupt-bytes = bit rot)
``loader.episode``    ``data/loader.py`` — episode-batch assembly (raise =
                      transient I/O; retried by the loader's retry wrapper)
``runner.step``       ``experiment/runner.py`` — per outer-step dispatch
                      (nan-loss = poisoned step observed by the NaN
                      sentinel, sigterm = preemption drill, delay, raise)
``serving.dispatch``  ``serving/engine.py`` — device dispatch of a batched
                      adapt/predict flush (raise trips the circuit breaker)
``serving.http``      ``serving/server.py`` — request handler, after the
                      body is drained (raise = handler bug -> 500, delay =
                      slow client path)
``serving.refine``    ``serving/engine.py`` — refine dispatch (nan-loss =
                      poisoned refinement observed by the rollback guard,
                      raise = dispatch failure, delay = slow refine)
==================  ========================================================

Spec grammar (one string per fault; ``;``-separated when packed into the
``HTYMP_FAULTS`` environment variable)::

    <site>=<kind>[:opt=val[,opt=val...]]

    kinds:    raise | corrupt-bytes | nan-loss | delay | sigterm
    options:  nth=N      fire only on the Nth call at the site (1-based)
              times=N    fire on the first N calls (after ``after``, if set)
              after=N    skip the first N calls (combine with times for a
                         burst: after=39,times=3 fires on calls 40-42)
              p=F        fire with probability F per call (seeded, so a
                         given (seed, call-index) always decides the same)
              delay_s=F  sleep duration for kind=delay

Examples::

    checkpoint.read=corrupt-bytes:nth=1
    runner.step=nan-loss:times=3
    runner.step=nan-loss:after=39,times=3
    runner.step=sigterm:nth=5
    serving.dispatch=raise:p=0.2
"""

import os
import signal
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..utils.locks import san_lock

KINDS = ("raise", "corrupt-bytes", "nan-loss", "delay", "sigterm")

#: The registered seam names — the single source of truth for everything
#: that fires or drills a fault site. ``graftlint`` GL303 statically checks
#: every ``fire("...")`` call site and every fault-spec string in the tree
#: against this tuple, so a typo'd drill (which would silently never fire)
#: is a lint error, not a no-op soak. Add the seam HERE (with its docstring
#: row above) before wiring a new ``fire()`` call.
SEAMS = (
    "checkpoint.write",
    "checkpoint.read",
    "loader.episode",
    "runner.step",
    "serving.dispatch",
    "serving.http",
    "serving.refine",
)

# env var merged into every config-built injector: drills on a live run
# without editing its config (docs/OPERATIONS.md "Drilling faults")
ENV_VAR = "HTYMP_FAULTS"


class InjectedFault(OSError):
    """Raised by ``kind=raise`` sites. An OSError subclass so transient-I/O
    retry wrappers (``resilience.retry.retry_call`` with the default
    ``retry_on=(OSError,)``) treat it exactly like the real thing."""


@dataclass
class FaultSpec:
    site: str
    kind: str
    p: float = 1.0
    nth: int = 0  # 0 = no nth trigger
    times: int = 0  # 0 = no first-N trigger
    after: int = 0  # skip the first N calls (shifts the times window)
    delay_s: float = 0.01

    @classmethod
    def parse(cls, text: str) -> "FaultSpec":
        head, _, opts = text.strip().partition(":")
        site, eq, kind = head.partition("=")
        if not eq or not site or kind not in KINDS:
            raise ValueError(
                f"bad fault spec {text!r}: want '<site>=<kind>[:opt=val,...]' "
                f"with kind in {KINDS}"
            )
        spec = cls(site=site.strip(), kind=kind.strip())
        for item in filter(None, (o.strip() for o in opts.split(","))):
            key, eq, val = item.partition("=")
            if not eq or key not in ("p", "nth", "times", "after", "delay_s"):
                raise ValueError(f"bad fault option {item!r} in spec {text!r}")
            setattr(spec, key, float(val) if key in ("p", "delay_s") else int(val))
        if not 0.0 <= spec.p <= 1.0:
            raise ValueError(f"fault p must be in [0, 1], got {spec.p} in {text!r}")
        return spec


class FaultInjector:
    """Holds parsed specs and decides, per call at a site, whether (and which)
    fault fires. Deterministic: probability triggers hash (seed, site,
    call-index), so the same configuration replays the same fault sequence.

    Side effects by kind:

    - ``raise``: raises :class:`InjectedFault`
    - ``delay``: calls the injected ``sleep`` (real by default, fake in tests)
    - ``sigterm``: sends SIGTERM to this process (the preemption drill — the
      runner's signal handler sees exactly what a real preemption sends)
    - ``corrupt-bytes``: only meaningful through :meth:`fire_bytes`, which
      returns a deterministically bit-flipped copy of the payload
    - ``nan-loss``: no side effect here — :meth:`fire` returns the kind and
      the runner's NaN sentinel treats the step's loss as non-finite
    """

    def __init__(
        self,
        specs: Sequence[FaultSpec] = (),
        seed: int = 0,
        sleep=time.sleep,
        kill=os.kill,
    ):
        self._by_site: Dict[str, List[FaultSpec]] = {}
        for spec in specs:
            self._by_site.setdefault(spec.site, []).append(spec)
        self.seed = seed
        self._sleep = sleep
        self._kill = kill
        # several sites fire from concurrent threads (loader prefetch pool,
        # batcher workers, ThreadingHTTPServer handlers) sharing one
        # injector: the call counters must be atomic or nth/times/p triggers
        # lose their deterministic-replay guarantee exactly at those seams
        self._lock = san_lock("FaultInjector._lock")
        self._calls: Dict[str, int] = {}
        # (site, kind) -> times fired; the observability surface for drills
        self.fired: Dict[str, int] = {}

    # -- construction ---------------------------------------------------

    @classmethod
    def from_specs(
        cls,
        specs: Sequence[str],
        seed: int = 0,
        include_env: bool = True,
        **kwargs,
    ) -> "FaultInjector":
        """Build from spec strings (e.g. ``Config.resilience.faults``), merging
        in the ``HTYMP_FAULTS`` env var (``;``-separated) unless told not to."""
        texts = list(specs)
        if include_env and os.environ.get(ENV_VAR):
            texts += [s for s in os.environ[ENV_VAR].split(";") if s.strip()]
        return cls([FaultSpec.parse(t) for t in texts], seed=seed, **kwargs)

    # -- firing ---------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return bool(self._by_site)

    def _decide(self, site: str) -> Optional[FaultSpec]:
        specs = self._by_site.get(site)
        if not specs:
            return None
        with self._lock:
            call = self._calls.get(site, 0) + 1
            self._calls[site] = call
            for spec in specs:
                if spec.nth and call != spec.nth:
                    continue
                if spec.after and call <= spec.after:
                    continue
                if spec.times and call > spec.after + spec.times:
                    continue
                if spec.p < 1.0:
                    # a pure function of (seed, site, call): replayable
                    mix = zlib.crc32(f"{self.seed}:{site}:{call}".encode())
                    if np.random.RandomState(mix).random_sample() >= spec.p:
                        continue
                self.fired[f"{site}:{spec.kind}"] = self.fired.get(f"{site}:{spec.kind}", 0) + 1
                return spec
            return None

    def fire(self, site: str) -> Optional[str]:
        """The seam entry point. Returns the fault kind that fired (None for
        no fault), after applying its side effect. Inert and allocation-free
        when no specs are configured."""
        if not self._by_site:
            return None
        spec = self._decide(site)
        if spec is None:
            return None
        if spec.kind == "raise":
            raise InjectedFault(f"injected fault at {site} (call {self._calls[site]})")
        if spec.kind == "delay":
            self._sleep(spec.delay_s)
        elif spec.kind == "sigterm":
            self._kill(os.getpid(), signal.SIGTERM)
        return spec.kind

    def fire_bytes(self, site: str, blob: bytes) -> bytes:
        """Seam entry point for byte-payload sites (checkpoint read/write):
        ``corrupt-bytes`` returns a deterministically corrupted copy (a run of
        flipped bytes mid-payload — what a torn write or bit rot looks like to
        the integrity check); other kinds behave as in :meth:`fire`."""
        if not self._by_site:
            return blob
        spec = self._decide(site)
        if spec is None:
            return blob
        if spec.kind == "raise":
            raise InjectedFault(f"injected fault at {site} (call {self._calls[site]})")
        if spec.kind == "delay":
            self._sleep(spec.delay_s)
        elif spec.kind == "sigterm":
            self._kill(os.getpid(), signal.SIGTERM)
        elif spec.kind == "corrupt-bytes":
            corrupted = bytearray(blob)
            mid = len(corrupted) // 2
            for i in range(mid, min(mid + 16, len(corrupted))):
                corrupted[i] ^= 0xFF
            return bytes(corrupted)
        return blob

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.fired)


#: Shared inert instance for default arguments — ``fire()`` on it is a single
#: falsy-dict check.
NULL_INJECTOR = FaultInjector()


def injector_from(resilience_cfg, **kwargs) -> FaultInjector:
    """Build an injector from a ``ResilienceConfig``-shaped object (duck-typed
    ``faults`` list + ``fault_seed``; resilience stays import-free of config)."""
    return FaultInjector.from_specs(
        getattr(resilience_cfg, "faults", ()) or (),
        seed=getattr(resilience_cfg, "fault_seed", 0),
        **kwargs,
    )
