#!/usr/bin/env python
"""Unified run report: telemetry.jsonl + events.jsonl + xplane device time.

Joins the three telemetry surfaces a run leaves behind into one report:

- ``logs/telemetry.jsonl`` (observability/telemetry.py) — step-phase
  histograms (data-wait / dispatch / settle / checkpoint / eval), throughput
  in episodes/s, provider snapshots (recompile guard, watchdog beat age);
- ``logs/events.jsonl`` (experiment/storage.py EventLog) — the resilience
  event stream (NaN skips/rollbacks, preemptions, wedges, degraded mesh);
- the ``jax.profiler`` xplane trace (``profile_dir``), when one was written —
  the XLA device-time breakdown (compute/dma fractions, measured FLOPs)
  that ``utils/profiling.py`` parses.

Host-phase coverage is the report's honesty check: the train-loop phase sums
(data-wait + dispatch + settle) over the summed epoch wall-clock. Near 1.0
the phase table explains the run; a low ratio means time is going somewhere
the phases don't span — say so rather than pretend.

Usage::

    python scripts/obs_report.py exps/<run> [--json] [--oneline]
        [--chrome-trace out.json] [--xplane-dir DIR]
    python scripts/obs_report.py --exps-root exps [--json]

``--json`` emits the full machine-readable report, ``--oneline`` one compact
JSON line (what ``scripts/sweep.sh`` appends per finished run),
``--chrome-trace`` copies the run's exported span trace (``logs/trace.json``,
Chrome/Perfetto-loadable) to the given path.

``--exps-root`` is the FLEET mode: every run directory under the root gets
the same per-run ``build_report`` pass, slimmed to its oneline form and
joined with the fleet scheduler's per-cell record (``fleet_cell.json``: rc
history, restarts, status) when one exists — one table/JSON over the whole
matrix, sharing the per-run code path rather than re-implementing it.

Import-light by design (stdlib + file-path-loaded repo modules; no jax, no
package import): a report over a finished run dir must never touch — or wait
on — a backend.
"""

import argparse
import importlib.util
import json
import os
import shutil
import sys
from typing import Any, Dict, List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PKG = os.path.join(_REPO_ROOT, "howtotrainyourmamlpytorch_tpu")

#: train-loop phases whose sums are compared against epoch wall-clock; eval
#: and checkpoint run outside the timed train loop
TRAIN_LOOP_PHASES = ("data_wait", "dispatch", "settle")


def _load_by_path(name: str, path: str):
    """File-path module load (the wait_for_tpu.py pattern): keeps this CLI
    free of the heavy package import (which pulls jax)."""
    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


try:
    exit_codes = _load_by_path("htymp_exit_codes", os.path.join(_PKG, "exit_codes.py"))
    _RC_OK, _RC_USAGE = exit_codes.OK, exit_codes.USAGE
except Exception:  # standalone copy of scripts/: the historical literals hold
    _RC_OK, _RC_USAGE = 0, 2


def _read_jsonl(path: str):
    """Parse a jsonl file, skipping (and counting) torn lines: a run killed
    hard mid-append leaves a partial final line, and this report must
    degrade on exactly those runs, never die on them."""
    records: List[Dict[str, Any]] = []
    torn = 0
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                torn += 1
    return records, torn


def _device_breakdown(xplane_dir: Optional[str]) -> Optional[Dict[str, Any]]:
    if not xplane_dir or not os.path.isdir(xplane_dir):
        return None
    try:
        profiling = _load_by_path(
            "htymp_profiling", os.path.join(_PKG, "utils", "profiling.py")
        )
        return profiling.device_time_breakdown(xplane_dir)
    except Exception as exc:  # noqa: BLE001 — the join degrades, never dies
        return {"error": f"xplane parse failed: {exc!r}"}


def _profile_dir_from_config(run_dir: str) -> Optional[str]:
    """``profile_dir`` out of the run's saved config.yaml without a yaml
    dependency surprise: the value is a plain scalar on its own line."""
    path = os.path.join(run_dir, "config.yaml")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        for line in f:
            if line.startswith("profile_dir:"):
                value = line.split(":", 1)[1].strip().strip("'\"")
                return value or None
    return None


def _aggregate_compile_ledger(entries: List[Dict[str, Any]]) -> Dict[str, Any]:
    """The compile-tax section: per-program lower/compile seconds, build
    counts, persistent-cache hits, and priced FLOPs, aggregated from
    ``logs/compile_ledger.jsonl``. Deliberately re-implements
    ``CompileLedger.summary()``'s aggregation: this script is import-light
    (no package import, no jax — it must run against a run dir from a
    wedged box), so it cannot replay entries through the ledger class.
    Keep the two shapes in sync."""
    by: Dict[str, Dict[str, Any]] = {}
    for e in entries:
        agg = by.setdefault(
            str(e.get("program", "?")),
            {
                "builds": 0,
                "lower_s": 0.0,
                "compile_s": 0.0,
                "total_s": 0.0,
                "cache_hits": 0,
                "errors": 0,
                "flops": None,
            },
        )
        agg["builds"] += 1
        agg["lower_s"] = round(agg["lower_s"] + (e.get("lower_s") or 0.0), 3)
        agg["compile_s"] = round(agg["compile_s"] + (e.get("compile_s") or 0.0), 3)
        agg["total_s"] = round(agg["total_s"] + (e.get("total_s") or 0.0), 3)
        if (e.get("persistent_cache") or {}).get("hit"):
            agg["cache_hits"] += 1
        if e.get("error"):
            agg["errors"] += 1
        if e.get("flops"):
            agg["flops"] = e["flops"]
        # the memory column (ISSUE 12): per-program argument/output/temp/
        # peak bytes + donated (aliased) bytes off memory_analysis; latest
        # build wins, like flops
        mem = e.get("memory") or {}
        for src, dst in (
            ("argument_bytes", "argument_bytes"),
            ("output_bytes", "output_bytes"),
            ("temp_bytes", "temp_bytes"),
            ("peak_bytes", "peak_bytes"),
            ("alias_bytes", "donated_bytes"),
        ):
            if mem.get(src) is not None:
                agg[dst] = mem[src]
    peaks = [p["peak_bytes"] for p in by.values() if p.get("peak_bytes")]
    donated = [p["donated_bytes"] for p in by.values() if p.get("donated_bytes")]
    return {
        "entries": len(entries),
        "programs": len(by),
        "total_lower_s": round(sum(p["lower_s"] for p in by.values()), 3),
        "total_compile_s": round(sum(p["compile_s"] for p in by.values()), 3),
        "total_s": round(sum(p["total_s"] for p in by.values()), 3),
        "cache_hits": sum(p["cache_hits"] for p in by.values()),
        "errors": sum(p["errors"] for p in by.values()),
        "peak_program_bytes": max(peaks) if peaks else None,
        "donated_bytes": max(donated) if donated else None,
        "by_program": by,
    }


def _hbm_from_session(session: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Peak-HBM watermark over one process session, from the memory
    provider rows the telemetry snapshots carry. None when the run had no
    available memory stats (CPU backends)."""
    peaks: List[float] = []
    headrooms: List[float] = []
    sampled = 0
    for record in session:
        mem = (record.get("providers") or {}).get("memory") or {}
        if not mem.get("available_devices"):
            continue
        sampled += 1
        if mem.get("peak_bytes_in_use_max") is not None:
            peaks.append(float(mem["peak_bytes_in_use_max"]))
        if mem.get("headroom_frac_min") is not None:
            headrooms.append(float(mem["headroom_frac_min"]))
    if not sampled:
        return None
    return {
        "snapshots_with_stats": sampled,
        "peak_bytes_in_use_max": max(peaks) if peaks else None,
        "peak_gib": round(max(peaks) / 2**30, 3) if peaks else None,
        "headroom_frac_min": min(headrooms) if headrooms else None,
    }


def build_report(
    run_dir: str,
    xplane_dir: Optional[str] = None,
    fleet_events: Optional[str] = None,
) -> Dict[str, Any]:
    logs_dir = os.path.join(run_dir, "logs")
    tel_path = os.path.join(logs_dir, "telemetry.jsonl")
    report: Dict[str, Any] = {
        "report": "obs",
        "run_dir": run_dir,
        "run": os.path.basename(os.path.normpath(run_dir)),
    }
    # fleet scaling decisions (ISSUE 18): an explicit --fleet-events path
    # (the supervisor's events.jsonl lives next to fleet_state.json, not in
    # a run dir) — replayed into a chronological decision table
    fleet_records: List[Dict[str, Any]] = []
    if fleet_events:
        if os.path.exists(fleet_events):
            fleet_records, torn_fleet = _read_jsonl(fleet_events)
            if torn_fleet:
                report["torn_fleet_event_lines"] = torn_fleet
        else:
            report["fleet_events_error"] = f"no such file: {fleet_events}"
    if not os.path.exists(tel_path):
        report["error"] = (
            "no logs/telemetry.jsonl — run predates the observability "
            "subsystem or had observability.enabled=false"
        )
        # a supervisor's decision log needs no telemetry — degrade to the
        # scaling table alone rather than dying on the missing file
        scaling = _scaling_from_events(fleet_records)
        if scaling is not None:
            report["scaling"] = scaling
        return report

    snapshots, torn = _read_jsonl(tel_path)
    if torn:
        report["torn_telemetry_lines"] = torn
    if not snapshots:
        report["error"] = (
            "logs/telemetry.jsonl holds no parseable snapshot "
            "(run died before its first snapshot, or every line is torn)"
        )
        return report
    # a resumed run APPENDS a fresh process session to the same
    # telemetry.jsonl, and each session's cumulative counters restart —
    # phase sums and wall-clock must be compared within ONE session, never
    # a suffix against the whole file. Snapshots carry a per-process
    # "session" id; split on it, falling back to a counter-reset heuristic
    # for id-less records.
    sessions: List[List[Dict[str, Any]]] = [[]]
    prev = None
    for record in snapshots:
        if prev is not None:
            if "session" in record or "session" in prev:
                new_session = record.get("session") != prev.get("session")
            else:
                new_session = (
                    float(record.get("elapsed_s") or 0.0)
                    < float(prev.get("elapsed_s") or 0.0)
                    or int(record.get("steps") or 0) < int(prev.get("steps") or 0)
                )
            if new_session:
                sessions.append([])
        sessions[-1].append(record)
        prev = record
    session = sessions[-1]  # report the latest process session
    epochs_all = [s for s in snapshots if s.get("kind") == "epoch"]
    epochs = [s for s in session if s.get("kind") == "epoch"]
    last = session[-1]
    phases = last.get("phases", {})
    report.update(
        {
            "snapshots": len(snapshots),
            "sessions": len(sessions),
            "epochs": len(epochs_all),
            "session_epochs": len(epochs),
            "steps": last.get("steps"),
            "episodes": last.get("episodes"),
            "episodes_per_s": last.get("episodes_per_s"),
            "elapsed_s": last.get("elapsed_s"),
            "phases": phases,
            "providers": last.get("providers", {}),
            "dropped_spans": last.get("dropped_spans", 0),
            "mfu": last.get("mfu"),
        }
    )

    # peak HBM per session (observability/memory.py provider rows)
    hbm = _hbm_from_session(session)
    if hbm is not None:
        report["hbm"] = hbm

    # cold start (runner gauge: init -> first settled step) — the number
    # the AOT prewarm exists to shrink
    gauges = last.get("gauges") or {}
    if gauges.get("cold_start_s") is not None:
        report["cold_start_s"] = gauges["cold_start_s"]

    # compile tax (logs/compile_ledger.jsonl), scoped to the reported
    # session when the entries carry session ids
    ledger_path = os.path.join(logs_dir, "compile_ledger.jsonl")
    if os.path.exists(ledger_path):
        entries, torn_ledger = _read_jsonl(ledger_path)
        if torn_ledger:
            report["torn_ledger_lines"] = torn_ledger
        session_id = last.get("session")
        scoped = [e for e in entries if e.get("session") == session_id]
        report["compile_tax"] = _aggregate_compile_ledger(scoped or entries)
        if not scoped and entries:
            report["compile_tax"]["all_sessions"] = True
        # the prewarm slice of the tax: entries the AOT prewarm paid
        # (phase="prewarm") BEFORE the first step, vs compiles that leaked
        # into the run proper
        prewarmed = [e for e in (scoped or entries) if e.get("phase") == "prewarm"]
        if prewarmed:
            report["prewarm"] = {
                "programs": len({e.get("program") for e in prewarmed}),
                "seconds": round(sum(e.get("total_s") or 0.0 for e in prewarmed), 3),
                "cache_hits": sum(
                    1
                    for e in prewarmed
                    if (e.get("persistent_cache") or {}).get("hit")
                ),
                # deserialized straight from the executable store: skipped
                # tracing AND XLA (the deepest warm tier)
                "store_hits": sum(
                    1
                    for e in prewarmed
                    if (e.get("executable_store") or {}).get("hit")
                ),
            }

    # host-phase coverage vs the SAME session's epoch wall-clock (the
    # honesty check)
    train_wall_s = sum(float(e.get("train_wall_s") or 0.0) for e in epochs)
    loop_sum_s = sum(
        float(phases.get(p, {}).get("sum_ms") or 0.0) / 1e3
        for p in TRAIN_LOOP_PHASES
    )
    report["train_wall_s"] = round(train_wall_s, 3)
    report["train_phase_sum_s"] = round(loop_sum_s, 3)
    report["phase_coverage"] = (
        round(loop_sum_s / train_wall_s, 3) if train_wall_s > 0 else None
    )

    # events.jsonl: counts by name + the resilience-notable subset
    events_path = os.path.join(logs_dir, "events.jsonl")
    event_records: List[Dict[str, Any]] = []
    if os.path.exists(events_path):
        event_records, torn_events = _read_jsonl(events_path)
        if torn_events:
            report["torn_event_lines"] = torn_events
        counts: Dict[str, int] = {}
        for record in event_records:
            name = record.get("event", "epoch_stats")
            counts[name] = counts.get(name, 0) + 1
        report["events"] = counts
        notable = {
            k: v
            for k, v in counts.items()
            if k in ("nan_step_skipped", "nan_rollback", "nan_abort",
                     "preempted", "wedged", "wedge_checkpoint",
                     "degraded_mesh", "early_abort", "donation_refused",
                     "replica_death", "backend_out", "backend_in",
                     "drain_begin", "drain_complete",
                     "sessions_spilled", "sessions_rehydrated",
                     "refine_rollback", "session_quarantined")
        }
        if notable:
            report["notable_events"] = notable
        # serving/fleet lifecycle timeline (ISSUE 14): replica deaths,
        # gateway membership flaps, drain milestones, session
        # spill/rehydrate — chronological, so "when did r1 die and who
        # absorbed it" is answerable from the run dir after the fact
        serving_events = [
            {
                k: rec.get(k)
                for k in ("ts", "event", "replica", "backend", "reason",
                          "status", "routable", "count", "deadline_exceeded",
                          "spilled_sessions", "loaded", "stale", "corrupt",
                          "in_count", "tenant", "bytes")
                if rec.get(k) is not None
            }
            for rec in event_records
            if rec.get("event")
            in ("replica_death", "backend_out", "backend_in", "drain_begin",
                "drain_complete", "sessions_spilled", "sessions_rehydrated",
                "tenant_evicted")
        ]
        if serving_events:
            report["serving_events"] = serving_events
        # per-session refinement lifecycle (ISSUE 17)
        refinement = _refinement_from_events(event_records)
        if refinement is not None:
            report["refinement"] = refinement
        # donation bookkeeping (ISSUE 12): the audit table (donatable vs
        # donated bytes per planned program) and, when the aliasing
        # self-check refused donation, its verdict
        audit = next(
            (r for r in reversed(event_records)
             if r.get("event") == "donation_audit"),
            None,
        )
        if audit is not None:
            report["donation"] = {
                k: v for k, v in audit.items() if k not in ("ts", "event")
            }
        refused = next(
            (r for r in reversed(event_records)
             if r.get("event") == "donation_refused"),
            None,
        )
        if refused is not None:
            report.setdefault("donation", {})["refused"] = {
                k: v for k, v in refused.items() if k not in ("ts", "event")
            }

    # padding-waste accounting (ROADMAP 4d): the access log records every
    # request's true vs bucketed sample count — aggregate the wasted-FLOPs
    # fraction per (verb, bucket) so bucket-edge tuning has a number
    access_path = os.path.join(logs_dir, "access.jsonl")
    if os.path.exists(access_path):
        access_records, torn_access = _read_jsonl(access_path)
        if torn_access:
            report["torn_access_lines"] = torn_access
        padding = _padding_from_access(access_records)
        if padding is not None:
            report["padding"] = padding
        strategies = _strategies_from_access(access_records)
        if strategies is not None:
            report["strategies"] = strategies
        tenants = _tenants_from_access(access_records, event_records)
        if tenants is not None:
            report["tenants"] = tenants

    # the scaling table also replays off the run's own events.jsonl when a
    # supervisor shared it (component == "supervisor" rows)
    scaling = _scaling_from_events(fleet_records or event_records)
    if scaling is not None:
        report["scaling"] = scaling

    xplane_dir = xplane_dir or _profile_dir_from_config(run_dir)
    breakdown = _device_breakdown(xplane_dir)
    if breakdown is not None:
        report["device_breakdown"] = breakdown

    trace_path = os.path.join(logs_dir, "trace.json")
    report["trace_path"] = trace_path if os.path.exists(trace_path) else None
    return report


def _padding_from_access(records: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Per-(verb, bucket) padding waste off access-log lines. FLOPs scale
    with the PADDED sample count, so a bucket's wasted-FLOPs fraction is
    ``1 - true_samples / padded_samples`` over the requests it served;
    lines without both shape fields (cache hits, HTTP-layer failures, older
    logs) are skipped."""
    per_bucket: Dict[str, Dict[str, Any]] = {}
    total_true = total_padded = 0
    for r in records:
        bucket, true = r.get("bucket"), r.get("true_size")
        if not isinstance(bucket, int) or not isinstance(true, int) or bucket <= 0:
            continue
        key = f"{r.get('verb')}/{bucket}"
        row = per_bucket.setdefault(
            key, {"requests": 0, "true_samples": 0, "padded_samples": 0}
        )
        row["requests"] += 1
        row["true_samples"] += true
        row["padded_samples"] += bucket
        total_true += true
        total_padded += bucket
    if not per_bucket or not total_padded:
        return None
    for row in per_bucket.values():
        row["waste_frac"] = round(
            1.0 - row["true_samples"] / row["padded_samples"], 4
        )
    return {
        "by_bucket": dict(sorted(per_bucket.items())),
        "padding_waste_frac": round(1.0 - total_true / total_padded, 4),
    }


def _strategies_from_access(
    records: List[Dict[str, Any]],
) -> Optional[Dict[str, Any]]:
    """Per-strategy latency/outcome table off access-log lines — the
    post-hoc answer to "which adaptation tier ate the fleet, and at what
    latency". Lines without a strategy field (HTTP-layer failures, synthetic
    replica_death lines, pre-registry logs) are skipped; latency percentiles
    use each line's ``total_ms`` where present."""
    per: Dict[str, Dict[str, Any]] = {}
    latencies: Dict[str, List[float]] = {}
    for r in records:
        strategy = r.get("strategy")
        if not isinstance(strategy, str):
            continue
        row = per.setdefault(
            strategy, {"requests": 0, "by_verb": {}, "by_outcome": {}}
        )
        row["requests"] += 1
        verb, outcome = str(r.get("verb")), str(r.get("outcome"))
        row["by_verb"][verb] = row["by_verb"].get(verb, 0) + 1
        row["by_outcome"][outcome] = row["by_outcome"].get(outcome, 0) + 1
        total_ms = r.get("total_ms")
        if isinstance(total_ms, (int, float)):
            latencies.setdefault(strategy, []).append(float(total_ms))
    if not per:
        return None
    for strategy, vals in latencies.items():
        vals.sort()
        per[strategy]["p50_ms"] = round(vals[len(vals) // 2], 3)
        per[strategy]["p95_ms"] = round(vals[min(len(vals) - 1, int(len(vals) * 0.95))], 3)
    return dict(sorted(per.items()))


def _refinement_from_events(
    events: List[Dict[str, Any]],
) -> Optional[Dict[str, Any]]:
    """Per-session refinement table (ISSUE 17) replayed off events.jsonl:
    commits, rollbacks, quarantines and re-adapts per session, plus the
    committed-score trend (first -> last -> best) so "is this long-lived
    session actually getting better, or riding its rollback guard" is
    answerable from the run dir. Sessions are keyed by their short id;
    returns None for runs with no refinement traffic at all."""
    per: Dict[str, Dict[str, Any]] = {}

    def _row(session: str, rec: Dict[str, Any]) -> Dict[str, Any]:
        row = per.setdefault(
            session,
            {"refines": 0, "rollbacks": 0, "quarantines": 0, "readapts": 0,
             "scores": [], "strategy": rec.get("strategy")},
        )
        if isinstance(rec.get("tenant"), str):
            row["tenant"] = rec["tenant"]
        return row

    saw_refinement = False
    for rec in events:
        session = rec.get("session")
        if not isinstance(session, str):
            continue
        event = rec.get("event")
        if event == "refine_commit":
            saw_refinement = True
            row = _row(session, rec)
            row["refines"] += 1
            if isinstance(rec.get("score"), (int, float)):
                row["scores"].append(float(rec["score"]))
        elif event == "refine_rollback":
            saw_refinement = True
            row = _row(session, rec)
            row["rollbacks"] += 1
            row["last_streak"] = rec.get("streak")
        elif event == "session_quarantined":
            saw_refinement = True
            _row(session, rec)["quarantines"] += 1
        elif event == "session_readapted":
            # only interesting for sessions that refined: a plain cache
            # miss on a refine-free session is not refinement traffic
            _row(session, rec)["readapts"] += 1
    if not saw_refinement:
        return None
    table: Dict[str, Dict[str, Any]] = {}
    for session, row in sorted(per.items()):
        if not (row["refines"] or row["rollbacks"] or row["quarantines"]):
            continue
        scores = row.pop("scores")
        if scores:
            row["first_score"] = round(scores[0], 4)
            row["last_score"] = round(scores[-1], 4)
            row["best_score"] = round(min(scores), 4)
        table[session[:12]] = row
    return table or None


#: supervisor event names that ARE scaling decisions (serving/autoscaler.py
#: _event); health-gate chatter (adopt, adopt_found_dead) stays out
_SCALING_EVENTS = (
    "supervisor_start", "scale_up", "scale_down", "backend_died",
    "spawn_crash", "quarantine", "retune", "adopt_rollforward",
    "supervisor_stop",
)


def _scaling_from_events(
    events: List[Dict[str, Any]],
) -> Optional[List[Dict[str, Any]]]:
    """Chronological scaling-decision table (ISSUE 18) replayed off a fleet
    supervisor's events.jsonl: each decision with the signal values that
    triggered it, its outcome, and how long it took to settle — "why did
    the fleet grow at 14:02, and how fast" is answerable after the fact.
    Returns None when the stream holds no supervisor records at all."""
    rows: List[Dict[str, Any]] = []
    for rec in events:
        if rec.get("component") != "supervisor":
            continue
        if rec.get("event") not in _SCALING_EVENTS:
            continue
        row = {
            k: rec.get(k)
            for k in ("ts", "event", "slot", "reason", "outcome", "settle_s",
                      "drain", "drain_rc", "backoff_s", "crashes", "pid",
                      "mode", "target", "adopted", "rolled_forward",
                      "spilled_sessions", "overrides", "improvement",
                      "waste_frac_before", "waste_frac_after", "ticks")
            if rec.get(k) is not None
        }
        signals = rec.get("signals")
        if isinstance(signals, dict) and signals:
            row["signals"] = signals
        rows.append(row)
    return rows or None


def _tenants_from_access(
    records: List[Dict[str, Any]],
    events: List[Dict[str, Any]],
) -> Optional[Dict[str, Any]]:
    """Per-tenant latency/outcome/resident-bytes table. Request rows come
    off access-log lines (lines without a tenant field — single-tenant
    deployments, HTTP-layer failures — count under ``default``); paging
    rows replay the pager's ``tenant_paged_in``/``tenant_evicted`` events
    so end-of-run master resident bytes are answerable from the run dir.
    Returns None for runs with no tenant traffic and no paging at all."""
    per: Dict[str, Dict[str, Any]] = {}
    latencies: Dict[str, List[float]] = {}
    saw_tenant_field = False

    def _row(tenant: str) -> Dict[str, Any]:
        return per.setdefault(
            tenant,
            {"requests": 0, "by_verb": {}, "by_outcome": {},
             "page_ins": 0, "evictions": 0, "resident_bytes": 0},
        )

    for r in records:
        if not isinstance(r.get("verb"), str):
            continue
        tenant = r.get("tenant")
        if isinstance(tenant, str):
            saw_tenant_field = True
        else:
            tenant = "default"
        row = _row(tenant)
        row["requests"] += 1
        verb, outcome = str(r.get("verb")), str(r.get("outcome"))
        row["by_verb"][verb] = row["by_verb"].get(verb, 0) + 1
        row["by_outcome"][outcome] = row["by_outcome"].get(outcome, 0) + 1
        total_ms = r.get("total_ms")
        if isinstance(total_ms, (int, float)):
            latencies.setdefault(tenant, []).append(float(total_ms))
    saw_paging = False
    for e in events:
        tenant, nbytes = e.get("tenant"), e.get("bytes")
        if not isinstance(tenant, str) or not isinstance(nbytes, int):
            continue
        if e.get("event") == "tenant_paged_in":
            saw_paging = True
            row = _row(tenant)
            row["page_ins"] += 1
            row["resident_bytes"] += nbytes
        elif e.get("event") == "tenant_evicted":
            saw_paging = True
            row = _row(tenant)
            row["evictions"] += 1
            row["resident_bytes"] = max(0, row["resident_bytes"] - nbytes)
    if not saw_tenant_field and not saw_paging:
        return None
    for tenant, vals in latencies.items():
        vals.sort()
        per[tenant]["p50_ms"] = round(vals[len(vals) // 2], 3)
        per[tenant]["p95_ms"] = round(
            vals[min(len(vals) - 1, int(len(vals) * 0.95))], 3
        )
    return dict(sorted(per.items()))


def _fmt_mib(n: Optional[float]) -> str:
    """bytes -> MiB with 2 decimals, '-' for unknown."""
    if n is None:
        return "-"
    return f"{n / 2**20:.2f}"


def oneline(report: Dict[str, Any]) -> str:
    """One compact JSON line per run for sweep logs."""
    phases = report.get("phases", {})
    compile_tax = report.get("compile_tax") or {}
    hbm = report.get("hbm") or {}
    slim = {
        "report": "obs",
        "run": report.get("run"),
        "error": report.get("error"),
        "epochs": report.get("epochs"),
        "episodes_per_s": report.get("episodes_per_s"),
        "mfu": report.get("mfu"),
        "cold_start_s": report.get("cold_start_s"),
        "prewarm_s": (report.get("prewarm") or {}).get("seconds"),
        "compile_tax_s": compile_tax.get("total_s"),
        "peak_program_bytes": compile_tax.get("peak_program_bytes"),
        "peak_hbm_gib": hbm.get("peak_gib"),
        "padding_waste": (report.get("padding") or {}).get("padding_waste_frac"),
        "phase_coverage": report.get("phase_coverage"),
        "phase_p50_ms": {k: v.get("p50_ms") for k, v in phases.items()},
        "notable_events": report.get("notable_events"),
    }
    return json.dumps({k: v for k, v in slim.items() if v is not None})


def _slim_run_row(report: Dict[str, Any], run_dir: str) -> Dict[str, Any]:
    """One fleet-table row: the oneline fields + the fleet scheduler's
    per-cell record (rc/restarts) when the run was fleet-driven."""
    row = json.loads(oneline(report))
    cell_path = os.path.join(run_dir, "fleet_cell.json")
    if os.path.exists(cell_path):
        try:
            with open(cell_path) as f:
                cell = json.load(f)
            row.update(
                {
                    "status": cell.get("status"),
                    "rcs": cell.get("rcs"),
                    "restarts": cell.get("restarts"),
                    "attempts": cell.get("attempts"),
                    "seed": cell.get("seed"),
                }
            )
        except (OSError, json.JSONDecodeError) as exc:
            row["fleet_cell_error"] = repr(exc)
    return row


def build_fleet_report(exps_root: str) -> Dict[str, Any]:
    """Aggregate every run dir under ``exps_root`` through the per-run
    ``build_report`` path. A directory counts as a run when it has a
    ``logs/`` subdirectory; runs predating the observability subsystem
    degrade to their error row rather than being skipped silently."""
    rows: List[Dict[str, Any]] = []
    for name in sorted(os.listdir(exps_root)):
        run_dir = os.path.join(exps_root, name)
        if not os.path.isdir(os.path.join(run_dir, "logs")):
            continue
        rows.append(_slim_run_row(build_report(run_dir), run_dir))
    report: Dict[str, Any] = {
        "report": "fleet_obs",
        "exps_root": exps_root,
        "runs": rows,
        "n_runs": len(rows),
    }
    fleet_path = os.path.join(exps_root, "fleet_report.json")
    if os.path.exists(fleet_path):
        try:
            with open(fleet_path) as f:
                fleet = json.load(f)
            report["fleet"] = {
                k: fleet.get(k)
                for k in ("spec", "done", "diverged", "failed", "skipped", "ok")
            }
        except (OSError, json.JSONDecodeError) as exc:
            report["fleet_report_error"] = repr(exc)
    return report


def render_fleet_human(report: Dict[str, Any]) -> str:
    lines = [f"== fleet report: {report['exps_root']} ({report['n_runs']} runs) =="]
    if report.get("fleet"):
        f = report["fleet"]
        lines.append(
            f"scheduler: spec={f.get('spec')} done={f.get('done')} "
            f"diverged={f.get('diverged')} failed={f.get('failed')} "
            f"skipped={f.get('skipped')} ok={f.get('ok')}"
        )
    lines.append(
        f"{'run':<34} {'status':<9} {'rcs':<12} {'rst':>3} {'epochs':>6} "
        f"{'eps/s':>8} {'cov':>5}  notable"
    )
    for row in report["runs"]:
        notable = row.get("notable_events") or {}
        rcs = ",".join(str(r) for r in (row.get("rcs") or [])) or "-"
        lines.append(
            f"{str(row.get('run'))[:34]:<34} "
            f"{str(row.get('status') or ('err' if row.get('error') else '-')):<9} "
            f"{rcs:<12} {str(row.get('restarts', '-')):>3} "
            f"{str(row.get('epochs', '-')):>6} "
            f"{str(row.get('episodes_per_s', '-')):>8} "
            f"{str(row.get('phase_coverage', '-')):>5}  "
            + (" ".join(f"{k}={v}" for k, v in sorted(notable.items())) or "-")
        )
    return "\n".join(lines)


def _render_scaling(report: Dict[str, Any], lines: List[str]) -> None:
    scaling = report.get("scaling")
    if not scaling:
        return
    lines.append(
        "-- fleet scaling decisions (supervisor events.jsonl, "
        "chronological) --"
    )
    for rec in scaling:
        ts = rec.get("ts")
        stamp = f"{ts:.3f}" if isinstance(ts, (int, float)) else "-"
        signals = rec.get("signals") or {}
        sig = " ".join(f"{k}={v}" for k, v in sorted(signals.items()))
        detail = "  ".join(
            f"{k}={v}" for k, v in sorted(rec.items())
            if k not in ("ts", "event", "signals")
        )
        lines.append(
            f"  {stamp}  {rec.get('event'):<18} {detail}"
            + (f"  [{sig}]" if sig else "")
        )


def render_human(report: Dict[str, Any]) -> str:
    lines = [f"== run report: {report.get('run')} =="]
    if report.get("error"):
        lines.append(f"ERROR: {report['error']}")
        # the scaling table survives a telemetry-free dir (fleet mode has
        # no training run behind it)
        _render_scaling(report, lines)
        return "\n".join(lines)
    lines.append(
        f"epochs {report['epochs']}  steps {report['steps']}  "
        f"episodes {report['episodes']}  "
        f"throughput {report['episodes_per_s']} episodes/s  "
        f"elapsed {report['elapsed_s']}s"
    )
    if report.get("sessions", 1) > 1:
        lines.append(
            f"({report['sessions']} process sessions in telemetry.jsonl — "
            f"resumed run; steps/phases below are the last session's "
            f"{report['session_epochs']} epoch(s))"
        )
    phases = report.get("phases", {})
    if phases:
        lines.append("-- step phases (host) --")
        lines.append(
            f"{'phase':<12} {'count':>7} {'p50 ms':>9} {'p95 ms':>9} "
            f"{'max ms':>9} {'sum s':>9}"
        )
        for name in sorted(phases):
            s = phases[name]
            lines.append(
                f"{name:<12} {s['count']:>7} {s['p50_ms']:>9} {s['p95_ms']:>9} "
                f"{s['max_ms']:>9} {round(s['sum_ms'] / 1e3, 2):>9}"
            )
        cov = report.get("phase_coverage")
        lines.append(
            f"train-loop phase sum {report['train_phase_sum_s']}s over "
            f"{report['train_wall_s']}s epoch wall-clock"
            + (f" (coverage {cov})" if cov is not None else "")
        )
        if cov is not None and not 0.9 <= cov <= 1.1:
            lines.append(
                "  NOTE: coverage outside [0.9, 1.1] — phase spans do not "
                "account for the train loop; trust the trace, not this table"
            )
    if report.get("mfu") is not None:
        lines.append(f"live MFU (last snapshot): {report['mfu']}")
    if report.get("cold_start_s") is not None:
        prewarm = report.get("prewarm")
        lines.append(
            f"cold start {report['cold_start_s']}s (init -> first settled step)"
            + (
                f"; prewarm compiled {prewarm['programs']} programs in "
                f"{prewarm['seconds']}s ({prewarm.get('store_hits', 0)} store hits, "
                f"{prewarm['cache_hits']} cache hits)"
                if prewarm
                else ""
            )
        )
    tax = report.get("compile_tax")
    if tax:
        lines.append(
            f"-- compile tax ({tax['entries']} compiles, "
            f"{tax['total_s']}s total: {tax['total_lower_s']}s lower + "
            f"{tax['total_compile_s']}s compile; "
            f"{tax['cache_hits']} persistent-cache hits"
            + (", ALL sessions" if tax.get("all_sessions") else "")
            + ") --"
        )
        lines.append(
            f"{'program':<28} {'builds':>6} {'lower s':>8} {'compile s':>9} "
            f"{'hits':>5}  flops"
        )
        for name in sorted(tax["by_program"]):
            p = tax["by_program"][name]
            flops = f"{p['flops']:.3e}" if p.get("flops") else "-"
            lines.append(
                f"{name[:28]:<28} {p['builds']:>6} {p['lower_s']:>8} "
                f"{p['compile_s']:>9} {p['cache_hits']:>5}  {flops}"
            )
        # per-program memory (the ledger's memory_analysis columns): the
        # bytes side of every remat/donation choice
        mem_rows = {
            name: p
            for name, p in tax["by_program"].items()
            if p.get("peak_bytes") is not None
        }
        if mem_rows:
            lines.append(
                f"-- program memory (peak over programs: "
                f"{_fmt_mib(tax.get('peak_program_bytes'))} MiB) --"
            )
            lines.append(
                f"{'program':<28} {'args MiB':>9} {'out MiB':>8} "
                f"{'temp MiB':>9} {'peak MiB':>9} {'donated':>8}"
            )
            for name in sorted(mem_rows):
                p = mem_rows[name]
                lines.append(
                    f"{name[:28]:<28} {_fmt_mib(p.get('argument_bytes')):>9} "
                    f"{_fmt_mib(p.get('output_bytes')):>8} "
                    f"{_fmt_mib(p.get('temp_bytes')):>9} "
                    f"{_fmt_mib(p.get('peak_bytes')):>9} "
                    f"{_fmt_mib(p.get('donated_bytes')):>8}"
                )
    donation = report.get("donation")
    if donation:
        flags = donation.get("flags") or {}
        lines.append(
            f"-- donation audit -- donate_train_state="
            f"{flags.get('donate_train_state')} donate_batch="
            f"{flags.get('donate_batch')}; donated "
            f"{_fmt_mib(donation.get('donated_bytes'))} MiB, left on table "
            f"{_fmt_mib(donation.get('left_on_table_bytes'))} MiB --"
        )
        for row in donation.get("rows") or []:
            lines.append(
                f"  {row['program']:<24} donated={','.join(row['donated']) or '-'} "
                f"not_donated={','.join(row['not_donated']) or '-'} "
                f"left_on_table={_fmt_mib(row['left_on_table_bytes'])} MiB"
            )
        if donation.get("refused"):
            refused = donation["refused"]
            lines.append(
                f"  DONATION REFUSED by aliasing self-check: verdict="
                f"{refused.get('verdict')} worst_param_rel="
                f"{refused.get('worst_param_rel')}"
            )
    padding = report.get("padding")
    if padding:
        lines.append(
            f"-- serving padding waste (access.jsonl) -- overall "
            f"{padding['padding_waste_frac']} of padded FLOPs wasted --"
        )
        lines.append(
            f"{'verb/bucket':<20} {'requests':>8} {'true':>8} {'padded':>8} "
            f"{'waste':>7}"
        )
        for name, row in padding["by_bucket"].items():
            lines.append(
                f"{name[:20]:<20} {row['requests']:>8} {row['true_samples']:>8} "
                f"{row['padded_samples']:>8} {row['waste_frac']:>7}"
            )
    strategies = report.get("strategies")
    if strategies:
        lines.append("-- serving strategies (access.jsonl) --")
        lines.append(
            f"{'strategy':<12} {'requests':>8} {'p50_ms':>8} {'p95_ms':>8} "
            f"{'outcomes'}"
        )
        for name, row in strategies.items():
            outcomes = ",".join(
                f"{k}={v}" for k, v in sorted(row["by_outcome"].items())
            )
            lines.append(
                f"{name[:12]:<12} {row['requests']:>8} "
                f"{row.get('p50_ms', '-'):>8} {row.get('p95_ms', '-'):>8} "
                f"{outcomes}"
            )
    tenants = report.get("tenants")
    if tenants:
        lines.append("-- serving tenants (access.jsonl + events.jsonl) --")
        lines.append(
            f"{'tenant':<16} {'requests':>8} {'p50_ms':>8} {'p95_ms':>8} "
            f"{'page_ins':>8} {'evict':>6} {'res_bytes':>10}  {'outcomes'}"
        )
        for name, row in tenants.items():
            outcomes = ",".join(
                f"{k}={v}" for k, v in sorted(row["by_outcome"].items())
            )
            lines.append(
                f"{name[:16]:<16} {row['requests']:>8} "
                f"{row.get('p50_ms', '-'):>8} {row.get('p95_ms', '-'):>8} "
                f"{row['page_ins']:>8} {row['evictions']:>6} "
                f"{row['resident_bytes']:>10}  {outcomes}"
            )
    refinement = report.get("refinement")
    if refinement:
        lines.append("-- session refinement (events.jsonl) --")
        lines.append(
            f"{'session':<14} {'strategy':<10} {'refines':>7} {'rollbk':>6} "
            f"{'quar':>4} {'readapt':>7} {'first':>8} {'last':>8} {'best':>8}"
        )
        for name, row in refinement.items():
            lines.append(
                f"{name:<14} {str(row.get('strategy') or '-')[:10]:<10} "
                f"{row['refines']:>7} {row['rollbacks']:>6} "
                f"{row['quarantines']:>4} {row['readapts']:>7} "
                f"{row.get('first_score', '-'):>8} "
                f"{row.get('last_score', '-'):>8} "
                f"{row.get('best_score', '-'):>8}"
            )
    hbm = report.get("hbm")
    if hbm:
        lines.append(
            f"-- HBM watermark (session) -- peak "
            f"{hbm.get('peak_gib', '-')} GiB, min headroom "
            f"{hbm.get('headroom_frac_min', '-')} "
            f"({hbm['snapshots_with_stats']} sampled snapshots)"
        )
    if report.get("events"):
        lines.append("-- events.jsonl --")
        lines.append(
            "  " + "  ".join(f"{k}={v}" for k, v in sorted(report["events"].items()))
        )
        if report.get("notable_events"):
            lines.append(
                "  notable: "
                + "  ".join(f"{k}={v}" for k, v in sorted(report["notable_events"].items()))
            )
    if report.get("serving_events"):
        lines.append("-- serving/fleet lifecycle (chronological) --")
        for rec in report["serving_events"]:
            ts = rec.get("ts")
            stamp = f"{ts:.3f}" if isinstance(ts, (int, float)) else "-"
            detail = "  ".join(
                f"{k}={v}" for k, v in sorted(rec.items())
                if k not in ("ts", "event")
            )
            lines.append(f"  {stamp}  {rec.get('event'):<20} {detail}")
    _render_scaling(report, lines)
    dev = report.get("device_breakdown")
    if dev and "error" not in dev:
        lines.append("-- device time (xplane) --")
        lines.append(
            f"  busy {dev.get('device_busy_ms')}ms: compute {dev.get('compute_frac')} "
            f"dma {dev.get('dma_frac')} other {dev.get('other_frac')}"
        )
    elif dev:
        lines.append(f"-- device time: {dev['error']}")
    providers = report.get("providers") or {}
    if providers:
        lines.append("-- providers (last snapshot) --")
        for name, value in sorted(providers.items()):
            lines.append(f"  {name}: {json.dumps(value)}")
    if report.get("trace_path"):
        lines.append(
            f"Chrome trace: {report['trace_path']} "
            "(open in chrome://tracing or https://ui.perfetto.dev; "
            "or --chrome-trace OUT to copy it)"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "run_dir", nargs="?", help="experiment run directory (exps/<name>)"
    )
    parser.add_argument(
        "--exps-root",
        help="fleet mode: aggregate every run dir under this root into one "
        "table/JSON (joined with fleet_cell.json / fleet_report.json)",
    )
    parser.add_argument("--json", action="store_true", help="full JSON report")
    parser.add_argument(
        "--oneline", action="store_true", help="one compact JSON line (sweep logs)"
    )
    parser.add_argument(
        "--chrome-trace",
        metavar="OUT",
        help="copy the run's exported span trace (logs/trace.json) here",
    )
    parser.add_argument(
        "--xplane-dir",
        help="jax.profiler trace dir for the device-time join "
        "(default: the run config's profile_dir)",
    )
    parser.add_argument(
        "--fleet-events",
        metavar="PATH",
        help="a fleet supervisor's events.jsonl (scripts/fleet_serve.py "
        "--events): adds the chronological scaling-decision table — works "
        "against a telemetry-free dir too",
    )
    args = parser.parse_args(argv)
    if args.exps_root:
        if not os.path.isdir(args.exps_root):
            print(f"obs_report: no such exps root: {args.exps_root}", file=sys.stderr)
            return _RC_USAGE
        fleet_report = build_fleet_report(args.exps_root)
        if args.json or args.oneline:
            print(
                json.dumps(fleet_report)
                if args.oneline
                else json.dumps(fleet_report, indent=1)
            )
        else:
            print(render_fleet_human(fleet_report))
        return _RC_OK
    if not args.run_dir:
        print("obs_report: need a run dir or --exps-root", file=sys.stderr)
        return _RC_USAGE
    if not os.path.isdir(args.run_dir):
        print(f"obs_report: no such run dir: {args.run_dir}", file=sys.stderr)
        return _RC_USAGE
    report = build_report(
        args.run_dir, xplane_dir=args.xplane_dir,
        fleet_events=args.fleet_events,
    )
    if args.chrome_trace:
        src = report.get("trace_path")
        if src:
            shutil.copyfile(src, args.chrome_trace)
            report["chrome_trace_written"] = args.chrome_trace
        else:
            print(
                "obs_report: no logs/trace.json to export "
                "(observability disabled, or the run died before export)",
                file=sys.stderr,
            )
            return _RC_USAGE
    if args.oneline:
        print(oneline(report))
    elif args.json:
        print(json.dumps(report, indent=1))
    else:
        print(render_human(report))
    return _RC_OK if "error" not in report else _RC_USAGE


if __name__ == "__main__":
    sys.exit(main())
