"""Backbone dispatch by name (reference ``few_shot_learning_system.py:53-83``).

``vgg`` -> Conv-4 VGG(64 filters, 4 stages, pad, max-pool);
``resnet-4/8/12`` -> stem-less ResNet with [1,1,1,1]/[2,2,2,2]/[3,3,3,3] blocks;
``densenet-8/12`` -> stem-less DenseNet-BC with [2]*4/[3]*4 blocks.
"""

from typing import Tuple

from .densenet import build_densenet
from .model import Model
from .resnet import build_resnet
from .vgg import build_vgg

_RESNET_BLOCKS = {"resnet-4": (1, 1, 1, 1), "resnet-8": (2, 2, 2, 2), "resnet-12": (3, 3, 3, 3)}
_DENSENET_BLOCKS = {"densenet-8": (2, 2, 2, 2), "densenet-12": (3, 3, 3, 3)}

MODEL_NAMES = ("vgg",) + tuple(_RESNET_BLOCKS) + tuple(_DENSENET_BLOCKS)


def build_model(
    net: str,
    image_shape: Tuple[int, int, int],
    num_classes: int,
    conv_via_patches: bool = False,
    reduce_window_pool: bool = False,
    fuse_conv_bn: bool = False,
) -> Model:
    """``image_shape`` is (H, W, C) — NHWC, the TPU-native layout.

    ``conv_via_patches`` (Config.conv_via_patches, the parallel.tp_convs
    enabler) and ``reduce_window_pool`` (Config.max_pool_reduce_window) are
    baked into the returned model's ``apply`` — explicit per-model
    parameters, not process globals, so concurrently-live systems trace
    independent conventions. ``fuse_conv_bn`` (Config.precision.fuse_conv_bn)
    folds BN into the patches-GEMM epilogue — implemented for the vgg
    backbone (the flagship), rejected loudly elsewhere."""
    if net == "vgg":
        return build_vgg(
            image_shape,
            num_classes,
            num_stages=4,
            cnn_num_filters=64,
            max_pooling=True,
            conv_padding=True,
            norm_layer="batch_norm",
            conv_via_patches=conv_via_patches,
            reduce_window_pool=reduce_window_pool,
            fuse_conv_bn=fuse_conv_bn,
        )
    if fuse_conv_bn:
        raise ValueError(
            f"precision.fuse_conv_bn is implemented for the vgg backbone "
            f"only (got net={net!r}); disable the fuse or use vgg"
        )
    if net in _RESNET_BLOCKS:
        return build_resnet(
            image_shape, num_classes, blocks_per_stage=_RESNET_BLOCKS[net],
            conv_via_patches=conv_via_patches,
        )
    if net in _DENSENET_BLOCKS:
        return build_densenet(
            image_shape, num_classes, block_config=_DENSENET_BLOCKS[net],
            conv_via_patches=conv_via_patches,
        )
    raise ValueError(f"unknown net {net!r}; expected one of {MODEL_NAMES}")
