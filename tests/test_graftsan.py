"""Tier-1 drills for the graftsan lock-discipline sanitizer (tools/graftsan).

Three contracts pinned here:

- **seeded fixtures are caught deterministically** — an ABBA acquisition
  pattern trips ``lock_order_cycle`` the moment the second edge lands (no
  contention, no timing), and a ``Future.result`` under a held lock trips
  ``held_across_blocking`` through the patched stdlib seam;
- **the shipped tree is clean** — a sanitizer-armed in-process campaign
  slice reports zero violations, and the WeightPager page-in path (the one
  true positive GL210 surfaced, fixed in ``serving/tenancy.py``) stays
  inversion-free under a registry-locking fake;
- **off means off** — with the sanitizer disarmed the factories hand back
  plain stdlib primitives (bit-identical types, zero overhead) and the
  campaign writes no graftsan artifacts (test_chaos_smoke pins that half).
"""

import json
import os
import subprocess
import sys
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.resilience.campaign import run_campaign
from howtotrainyourmamlpytorch_tpu.serving.tenancy import WeightPager

from tools.graftsan import runtime

from tests.test_runner import toy_dataset  # noqa: F401

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def armed():
    runtime.arm()
    runtime.reset()
    yield runtime
    runtime.disarm()
    runtime.reset()


# -- seeded fixtures: caught, deterministically -----------------------------


def test_abba_cycle_is_caught_without_contention(armed):
    """A then B, later B then A — the classic ABBA. The cycle is flagged on
    the second edge's insert, with both acquisition stacks, without ever
    needing the two threads to actually contend."""
    a = armed.san_lock("FixtureA._lock")
    b = armed.san_lock("FixtureB._lock")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    cycles = [v for v in armed.violations() if v["kind"] == "lock_order_cycle"]
    assert len(cycles) == 1, armed.violations()
    v = cycles[0]
    assert {v["site_a"], v["site_b"]} == {"FixtureA._lock", "FixtureB._lock"}
    assert v["stack_b"] and v["reverse_edges"][0]["stack"]  # both sides
    assert v["event"] == "graftsan_violation"  # events.jsonl-ready as-is
    # deterministic: the same pattern again reports nothing new (deduped)
    with b:
        with a:
            pass
    assert (
        len([x for x in armed.violations() if x["kind"] == "lock_order_cycle"])
        == 1
    )


def test_held_across_dispatch_is_caught_via_patched_seam(armed):
    """``Future.result`` while holding a lock — the held-across-dispatch
    wedge shape (EngineReplica.dispatch guards against it with
    ``note_blocking``). The patched stdlib seam catches it even though the
    future is already done, so the drill never risks an actual hang."""
    lock = armed.san_lock("FixtureC._lock")
    pool = ThreadPoolExecutor(max_workers=1)
    try:
        fut = pool.submit(lambda: 7)
        assert fut.result(timeout=5) == 7  # no lock held: clean
        with lock:
            assert fut.result(timeout=5) == 7  # held: violation
    finally:
        pool.shutdown(wait=True)
    held = [v for v in armed.violations() if v["kind"] == "held_across_blocking"]
    assert len(held) == 1, armed.violations()
    assert "FixtureC._lock" in held[0]["held"]
    assert "Future.result" in held[0]["blocking"]


def test_declared_order_inversion_is_caught(armed):
    """order.toml ranks registry before pager; nesting them the wrong way
    round is an inversion even with no reverse edge recorded yet."""
    pager = armed.san_lock("WeightPager._lock")
    registry = armed.san_lock("TenantRegistry._lock")
    with pager:
        with registry:
            pass
    kinds = {v["kind"] for v in armed.violations()}
    assert "lock_order_inversion" in kinds, armed.violations()


def test_thread_leak_audit_names_the_leak(armed):
    stop = threading.Event()
    t = threading.Thread(target=stop.wait, name="leaky-fixture")
    t.start()
    try:
        leaked = armed.audit_thread_leaks("drill", baseline=set())
        assert "leaky-fixture" in leaked
        leaks = [v for v in armed.violations() if v["kind"] == "thread_leak"]
        assert leaks and "leaky-fixture" in leaks[0]["threads"]
    finally:
        stop.set()
        t.join()
    # joined threads are not leaks
    baseline = {x.ident for x in threading.enumerate()}
    assert armed.audit_thread_leaks("after-join", baseline=baseline) == []


# -- the shipped tree: clean under the armed sanitizer ----------------------


def test_weight_pager_page_in_holds_no_lock_across_registry(armed):
    """Regression for the GL210 true positive: WeightPager.resident used to
    hold the pager lock across ``registry.host_state`` (registry lock +
    checkpoint disk read) — a declared-order inversion and an I/O convoy.
    The fixed path fetches outside the lock; a registry-locking fake under
    the armed sanitizer proves it, and the old shape still trips."""
    class FakeRegistry:
        def __init__(self):
            self._lock = armed.san_lock("TenantRegistry._lock")

        def host_state(self, tenant):
            with self._lock:
                return {"w": np.ones((2, 2), np.float32)}, {"tenant": tenant}

    pager = WeightPager(FakeRegistry(), template=None)
    state = pager.resident("acme")
    assert state is not None and pager.page_ins == 1
    assert pager.resident("acme") is state  # hit path, still clean
    assert [
        v
        for v in armed.violations()
        if v["kind"] in ("lock_order_cycle", "lock_order_inversion")
    ] == [], armed.violations()
    # the pre-fix shape (registry fetched under the pager lock) is exactly
    # what the sanitizer exists to catch — prove this test has teeth
    with pager._lock:
        pager.registry.host_state("evil")
    assert any(
        v["kind"] == "lock_order_inversion" for v in armed.violations()
    )


def test_sanitized_mini_campaign_reports_zero_violations(toy_dataset, tmp_path):
    """The tier-1 slice of the acceptance run: a seeded in-process campaign
    with ``sanitize=True`` arms every lock built through the factories and
    must come back with a zero-violation sanitizer verdict block."""
    verdict = run_campaign(
        str(tmp_path),
        episodes=2,
        seed=0,
        data_root=toy_dataset,
        include_subprocess=False,
        sanitize=True,
        log=lambda m: None,
    )
    assert verdict["ok"], verdict["violations"]
    san = verdict["sanitizer"]
    assert san["armed"] is True
    assert san["violations"] == 0 and san["by_kind"] == {}, san
    assert san["torn_lines"] == 0
    # the campaign restores the caller's env and disarms on the way out
    assert os.environ.get("HTYMP_GRAFTSAN") != "1"
    assert "HTYMP_GRAFTSAN_LOG" not in os.environ
    runtime.reset()


# -- off means off ----------------------------------------------------------


def test_sanitizer_off_hands_out_plain_stdlib_primitives(monkeypatch):
    monkeypatch.delenv("HTYMP_GRAFTSAN", raising=False)
    runtime.disarm()
    assert not runtime.enabled()
    assert type(runtime.san_lock("X._lock")) is type(threading.Lock())
    assert type(runtime.san_rlock("X._rlock")) is type(threading.RLock())
    assert type(runtime.san_condition("X._cond")) is threading.Condition
    # the package shim agrees (this is what serving/+resilience/ import)
    from howtotrainyourmamlpytorch_tpu.utils import locks

    assert locks.GRAFTSAN_AVAILABLE
    assert type(locks.san_lock("Y._lock")) is type(threading.Lock())
    locks.note_blocking("Y.dispatch")  # no-op, records nothing
    assert runtime.violations() == []


# -- the verdict CLI --------------------------------------------------------


def test_graftsan_report_cli_contract(tmp_path):
    """``scripts/graftsan_report.py``: one JSON line, rc 1 on violations,
    rc 0 clean, rc 2 usage."""
    log = tmp_path / "graftsan.jsonl"
    log.write_text(
        json.dumps(
            {
                "event": "graftsan_violation",
                "kind": "lock_order_cycle",
                "site_a": "A._lock",
                "site_b": "B._lock",
            }
        )
        + "\n"
    )
    proc = subprocess.run(
        [sys.executable, "scripts/graftsan_report.py", "--log", str(log)],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode == 1, proc.stderr
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1
    payload = json.loads(lines[0])
    assert payload["ok"] is False
    assert payload["by_kind"] == {"lock_order_cycle": 1}

    run_dir = tmp_path / "run"
    (run_dir / "logs").mkdir(parents=True)
    (run_dir / "logs" / "events.jsonl").write_text(
        json.dumps({"event": "epoch_end", "epoch": 0}) + "\n"
    )
    proc = subprocess.run(
        [sys.executable, "scripts/graftsan_report.py", "--run-dir", str(run_dir)],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert json.loads(proc.stdout.strip())["ok"] is True

    proc = subprocess.run(
        [sys.executable, "scripts/graftsan_report.py"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode == 2
