"""Differentiable inner optimizers vs torch.optim as an independent oracle
(SURVEY.md §4: 'inner SGD/Adam/Rprop differentiable-step math vs hand-computed
examples'), plus differentiability of the hyperparameters (LSLR)."""

import jax
import jax.numpy as jnp
import numpy as np
import torch

from howtotrainyourmamlpytorch_tpu.ops import build_inner_optimizer


def _run_torch_steps(opt_cls, p0, grads, n_steps, **kwargs):
    p = torch.tensor(p0, requires_grad=True)
    opt = opt_cls([p], **kwargs)
    out = []
    for i in range(n_steps):
        opt.zero_grad()
        p.grad = torch.tensor(grads[i])
        opt.step()
        out.append(p.detach().numpy().copy())
    return out


def _run_ours(kind, p0, grads, n_steps, **kwargs):
    opt = build_inner_optimizer(kind, **kwargs)
    params = {"w": jnp.array(p0)}
    hparams = opt.init_hparams(params)
    state = opt.init_state(params, hparams)
    out = []
    for i in range(n_steps):
        params, state = opt.update({"w": jnp.array(grads[i])}, state, params, hparams)
        out.append(np.asarray(params["w"]))
    return out


def test_sgd_matches_torch():
    rng = np.random.RandomState(0)
    p0 = rng.randn(4).astype(np.float32)
    grads = [rng.randn(4).astype(np.float32) for _ in range(3)]
    theirs = _run_torch_steps(torch.optim.SGD, p0, grads, 3, lr=0.1)
    ours = _run_ours("sgd", p0, grads, 3, lr=0.1)
    for a, b in zip(ours, theirs):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_adam_matches_torch():
    rng = np.random.RandomState(1)
    p0 = rng.randn(4).astype(np.float32)
    grads = [rng.randn(4).astype(np.float32) for _ in range(5)]
    theirs = _run_torch_steps(torch.optim.Adam, p0, grads, 5, lr=0.1, betas=(0.5, 0.5))
    ours = _run_ours("adam", p0, grads, 5, lr=0.1, beta1=0.5, beta2=0.5)
    for a, b in zip(ours, theirs):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_rprop_matches_torch():
    rng = np.random.RandomState(2)
    p0 = rng.randn(6).astype(np.float32)
    grads = [rng.randn(6).astype(np.float32) for _ in range(6)]
    theirs = _run_torch_steps(torch.optim.Rprop, p0, grads, 6, lr=0.1)
    ours = _run_ours("rprop", p0, grads, 6, lr=0.1)
    for a, b in zip(ours, theirs):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_lr_is_differentiable_through_update():
    """The LSLR point: d(final param)/d(lr) must flow (reference makes lrs
    outer-trainable via higher override — few_shot_learning_system.py:226-237)."""
    opt = build_inner_optimizer("sgd", lr=0.1)
    params = {"w": jnp.array([1.0, 2.0])}
    grads = {"w": jnp.array([0.5, -0.5])}

    def fn(lr_scalar):
        hparams = {"lr": {"w": lr_scalar}}
        state = opt.init_state(params, hparams)
        new_params, _ = opt.update(grads, state, params, hparams)
        return jnp.sum(new_params["w"] ** 2)

    g = jax.grad(fn)(jnp.asarray(0.1))
    # d/dlr sum((p - lr*g)^2) = sum(2*(p-lr*g)*(-g))
    expected = float(2 * ((1 - 0.05) * -0.5 + (2 + 0.05) * 0.5))
    np.testing.assert_allclose(float(g), expected, rtol=1e-5)


def test_adam_betas_differentiable():
    # NB: with identical gradients at every step, d(update)/d(beta1) is exactly
    # zero (bias correction cancels beta1 analytically), so use distinct grads.
    opt = build_inner_optimizer("adam", lr=0.1, beta1=0.5, beta2=0.5)
    params = {"w": jnp.array([1.0])}
    g1 = {"w": jnp.array([0.3])}
    g2 = {"w": jnp.array([-0.7])}

    def fn(b1):
        hparams = {
            "lr": {"w": jnp.asarray(0.1)},
            "beta1": {"w": b1},
            "beta2": {"w": jnp.asarray(0.5)},
        }
        state = opt.init_state(params, hparams)
        p1, state = opt.update(g1, state, params, hparams)
        p2, _ = opt.update(g2, state, p1, hparams)
        return p2["w"][0]

    g = jax.grad(fn)(jnp.asarray(0.5))
    assert np.isfinite(float(g)) and abs(float(g)) > 0


def test_adam_second_order_finite_at_zero_grad_elements():
    """Regression: second-order meta-grads through the FIRST adam inner step
    must be finite even for parameter elements whose inner gradient is
    exactly zero (real on Omniglot — kernel taps that only ever see constant
    background). exp_avg_sq starts at 0 there, and an unclamped sqrt makes
    sqrt'(0) = inf appear in the backward, where inf * 0 = NaN poisoned the
    first outer update (observed in the round-4 CPU smoke: every loss after
    iteration 0 NaN, betas.csv all-NaN)."""
    opt = build_inner_optimizer("adam", lr=0.1, beta1=0.5, beta2=0.5)

    def meta_loss(p):
        # inner loss touches only w[0]; w[1]'s inner grad is exactly 0
        def inner_loss(q):
            return q["w"][0] ** 2

        g = jax.grad(inner_loss)(p)
        hparams = opt.init_hparams(p)
        state = opt.init_state(p, hparams)
        p1, _ = opt.update(g, state, p, hparams)
        return jnp.sum(p1["w"] ** 2)

    params = {"w": jnp.array([0.7, -0.3])}
    g = jax.grad(meta_loss)(params)
    assert np.all(np.isfinite(np.asarray(g["w"]))), g
    # and the forward math is unchanged where grads are nonzero
    loss = meta_loss(params)
    assert np.isfinite(float(loss))


def test_projection():
    opt = build_inner_optimizer("adam")
    h = {
        "lr": {"w": jnp.asarray(-0.5)},
        "beta1": {"w": jnp.asarray(1.5)},
        "beta2": {"w": jnp.asarray(-2.0)},
    }
    p = opt.project_hparams(h)
    np.testing.assert_allclose(float(p["lr"]["w"]), 1e-4, rtol=1e-5)
    np.testing.assert_allclose(float(p["beta1"]["w"]), 0.99, rtol=1e-5)
    np.testing.assert_allclose(float(p["beta2"]["w"]), 1e-4, rtol=1e-5)
