from .maml import MAMLSystem, StepOutput, cosine_epoch_schedule  # noqa: F401
from .train_state import TrainState  # noqa: F401
