"""Pytree helpers shared across the framework."""

import jax
import jax.numpy as jnp
import numpy as np


def tree_scalars_like(tree, value, dtype=jnp.float32):
    """A tree with the same structure as ``tree`` whose leaves are scalars.

    Used for per-tensor learnable inner-opt hyperparameters (LSLR): the
    reference creates one optimizer param-group *per parameter tensor*
    (reference ``few_shot_learning_system.py:94-107``), so each leaf of the
    parameter tree gets its own scalar lr / beta.
    """
    return jax.tree.map(lambda _: jnp.asarray(value, dtype=dtype), tree)


def tree_zeros_like(tree):
    return jax.tree.map(jnp.zeros_like, tree)


def tree_full_like(tree, value):
    return jax.tree.map(lambda p: jnp.full_like(p, value), tree)


def tree_count_params(tree):
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(tree))


def tree_clip(tree, lo, hi):
    return jax.tree.map(lambda p: jnp.clip(p, lo, hi), tree)


def tree_to_numpy(tree):
    return jax.tree.map(lambda x: np.asarray(x), tree)


def tree_allclose(a, b, rtol=1e-5, atol=1e-7):
    leaves_a, treedef_a = jax.tree.flatten(a)
    leaves_b, treedef_b = jax.tree.flatten(b)
    if treedef_a != treedef_b:
        return False
    return all(
        np.allclose(np.asarray(x), np.asarray(y), rtol=rtol, atol=atol)
        for x, y in zip(leaves_a, leaves_b)
    )


def named_leaves(tree, prefix=""):
    """Yield ``(dotted_name, leaf)`` pairs in deterministic traversal order.

    Used for parameter printouts (parity with the reference's named-parameter
    dump, reference ``few_shot_learning_system.py:116-122``) and for the
    ``lrs.csv`` column ordering.
    """
    if isinstance(tree, dict):
        for key in sorted(tree.keys()):
            yield from named_leaves(tree[key], f"{prefix}{key}.")
    elif isinstance(tree, (list, tuple)):
        for i, item in enumerate(tree):
            yield from named_leaves(item, f"{prefix}{i}.")
    else:
        yield prefix.rstrip("."), tree
