"""Multi-host serving fleet (ISSUE 14): gateway membership + routing units,
graceful-drain semantics, session spill/rehydrate, and THE cross-process
chaos drills — a real ``scripts/gateway.py`` subprocess fronting real serve
backends (``campaign.child_serve_main`` through the actual ``run_server``
SIGTERM drain path): kill -9 (availability survives, displaced sessions
re-adapt — never stale), SIGTERM drain (zero dropped in-flight requests +
a digest-verified spill -> rehydrate cache hit after restart), and a full
rolling restart under load with every non-200 resolvable to a gateway
access line.
"""

import json
import os
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu import exit_codes
from howtotrainyourmamlpytorch_tpu.config import Config, ServingConfig
from howtotrainyourmamlpytorch_tpu.core import MAMLSystem
from howtotrainyourmamlpytorch_tpu.data.synthetic import synthetic_batch
from howtotrainyourmamlpytorch_tpu.models import build_vgg
from howtotrainyourmamlpytorch_tpu.resilience.campaign import (
    Episode,
    _run_gateway_episode,
    make_serving_run_dir,
)
from howtotrainyourmamlpytorch_tpu.resilience.faults import FaultInjector
from howtotrainyourmamlpytorch_tpu.serving import (
    AdaptationEngine,
    Gateway,
    ServiceUnavailableError,
    ServingFrontend,
    SessionStore,
    UnknownAdaptationError,
    drain_exit_code,
    make_gateway_server,
)
from howtotrainyourmamlpytorch_tpu.serving import gateway as gateway_mod
from howtotrainyourmamlpytorch_tpu.serving import router as router_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_IMG = (28, 28, 1)


def test_rendezvous_has_one_implementation():
    """The in-process router and the multi-host gateway must agree where a
    session lives: the router's rendezvous_score IS the gateway's (single
    definition, re-exported) — not a lookalike that could drift."""
    assert router_mod.rendezvous_score is gateway_mod.rendezvous_score
    # process-stable: a pinned value, not just self-consistency
    assert gateway_mod.rendezvous_score("digest001", 0) == int.from_bytes(
        __import__("hashlib").blake2b(b"digest001|0", digest_size=8).digest(), "big"
    )


# ---------------------------------------------------------------------------
# membership hysteresis (pure units, no sockets)
# ---------------------------------------------------------------------------


def test_backend_membership_hysteresis_and_flaps():
    b = gateway_mod.Backend(0, "http://x", fail_threshold=2, pass_threshold=2)
    assert not b.is_in  # starts OUT: never seen healthy
    assert b.note_observation(True, "ok") is None  # 1/2 passes
    assert b.note_observation(True, "ok") == "in"
    assert b.is_in and b.flaps == 0  # first admission is not a flap
    # one failure is not enough to eject
    assert b.note_observation(False, "unreachable") is None
    assert b.is_in
    # a pass resets the failure streak
    assert b.note_observation(True, "ok") is None
    assert b.note_observation(False, "unreachable") is None
    assert b.note_observation(False, "unreachable") == "out"
    assert not b.is_in and b.flaps == 1
    # recovery: two consecutive passes readmit (and count a flap)
    assert b.note_observation(True, "ok") is None
    assert b.note_observation(True, "ok") == "in"
    assert b.flaps == 2
    snap = b.snapshot()
    assert snap["state"] == "in" and snap["flaps"] == 2


def test_gateway_routing_rendezvous_and_exclusion():
    g = Gateway(["http://a", "http://b", "http://c"], pass_threshold=1)
    for backend in g.backends:
        g.observe(backend, True, "ok")
    keys = [f"k{i:03d}" for i in range(120)]
    owners = {k: g.route(k).index for k in keys}
    assert set(owners.values()) == {0, 1, 2}
    assert all(g.route(k).index == owners[k] for k in keys)  # deterministic
    # exclusion remaps ONLY the excluded backend's keys
    for k in keys:
        alt = g.route(k, exclude={owners[k]})
        assert alt is not None and alt.index != owners[k]
    other = {k: g.route(k).index for k in keys if owners[k] != 0}
    g.observe(g.backends[0], False, "unreachable")
    g.observe(g.backends[0], False, "unreachable")
    assert not g.backends[0].is_in
    assert all(g.route(k).index == other[k] for k in other)  # no reshuffle
    g.close()


def test_gateway_draining_warming_are_not_routable_new_work():
    """A reachable backend whose healthz body says warming/draining is
    alive but must leave rotation (hysteresis applies) — the drain/rolling
    restart membership contract."""
    g = Gateway(["http://a", "http://b"], pass_threshold=1, fail_threshold=2)
    for backend in g.backends:
        g.observe(backend, True, "ok")
    assert g.in_count() == 2
    for _ in range(2):
        g.observe(g.backends[0], False, "draining")
    assert g.in_count() == 1
    assert g.backends[0].snapshot()["last_status"] == "draining"
    code, body = g.healthz()
    assert code == 200 and body["status"] == "degraded"
    for _ in range(2):
        g.observe(g.backends[1], False, "warming")
    code, body = g.healthz()
    assert code == 503 and body["status"] == "no_backend"
    g.close()


# ---------------------------------------------------------------------------
# proxy behavior over real sockets (fake jax-free backends)
# ---------------------------------------------------------------------------


class _FakeServe(BaseHTTPRequestHandler):
    """Scriptable fake serve backend: behavior comes from server.script."""

    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        pass

    def _send(self, code, body, headers=None):
        raw = json.dumps(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(raw)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(raw)

    def do_GET(self):  # noqa: N802
        self._send(200, {"status": "ok"})

    def do_POST(self):  # noqa: N802
        n = int(self.headers.get("Content-Length", 0))
        self.rfile.read(n)
        script = self.server.script  # type: ignore[attr-defined]
        code, body, headers = script(self.server.name, self.path)  # type: ignore[attr-defined]
        if self.server.delay_s:  # type: ignore[attr-defined]
            time.sleep(self.server.delay_s)  # type: ignore[attr-defined]
        self._send(code, body, headers)


def _spawn_fake(name, script, delay_s=0.0):
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeServe)
    srv.name = name
    srv.script = script
    srv.delay_s = delay_s
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_address[1]}"


def _post(url, payload, headers=None, timeout=10):
    import urllib.request

    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read()), dict(resp.headers.items())


def test_gateway_retry_with_exclusion_session_learning_and_access_log(tmp_path):
    """A 500 from the routed backend retries against the next-ranked live
    backend (counted); the adapt response teaches the session table so the
    session's predict follows its fast weights; every request logs ONE
    gateway access line carrying the backend field; backend refusals (503
    shed) pass through with Retry-After."""
    import urllib.error
    import urllib.request

    calls = {"s0": 0, "s1": 0}

    def script(name, path):
        calls[name] += 1
        if name == "s0":
            return 500, {"error": "boom"}, None
        if path == "/adapt":
            return 200, {"adaptation_id": "aid-9", "cached": False}, None
        return 200, {"probs": [[1.0]]}, None

    s0, u0 = _spawn_fake("s0", script)
    s1, u1 = _spawn_fake("s1", script)
    g = Gateway([u0, u1], health_interval_s=30.0, pass_threshold=1,
                log_dir=str(tmp_path))
    for backend in g.backends:
        g.observe(backend, True, "ok")
    server = make_gateway_server(g, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        # drive adapts until one rendezvous-routes to s0 first (500 -> retry)
        saw_retry = False
        for i in range(8):
            code, body, headers = _post(
                base + "/adapt", {"x_support": [i], "y_support": [i]}
            )
            assert code == 200
            assert headers["X-Gateway-Backend"] == "b1"  # s0 always 500s
            assert len(headers["X-Request-Id"]) == 32
            if g.metrics()["retries"] > 0:
                saw_retry = True
                break
        assert saw_retry, "no adapt ever routed to the failing backend first"
        # session affinity: the predict for aid-9 goes to b1 (learned), and
        # b1 answers without s0 seeing the request
        s0_calls = calls["s0"]
        code, body, headers = _post(
            base + "/predict", {"adaptation_id": "aid-9", "x_query": [1]}
        )
        assert code == 200 and headers["X-Gateway-Backend"] == "b1"
        assert calls["s0"] == s0_calls
        # backend refusal passes through with Retry-After, NOT retried
        s1.script = lambda name, path: (
            503, {"error": "queue full — shedding"}, {"Retry-After": "7"}
        )
        s0.script = s1.script
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(base + "/predict", {"adaptation_id": "aid-9", "x_query": [1]})
        assert err.value.code == 503
        assert err.value.headers["Retry-After"] == "7"
        # the gateway access log: one line per request, backend named
        g.access.close()
        with open(os.path.join(str(tmp_path), "access.jsonl")) as f:
            records = [json.loads(line) for line in f if line.strip()]
        assert all("backend" in r and "trace_id" in r for r in records)
        ok_lines = [r for r in records if r["outcome"] == "ok"]
        assert ok_lines and all(r["backend"] == "b1" for r in ok_lines)
        shed_lines = [r for r in records if r["outcome"] == "shed"]
        assert shed_lines and shed_lines[-1]["status"] == 503
    finally:
        server.shutdown()
        server.server_close()
        g.close()
        for srv in (s0, s1):
            srv.shutdown()
            srv.server_close()


def test_gateway_refine_requests_follow_session_affinity():
    """A refine (``/adapt`` with ``refine`` + ``session_id``) is SESSION
    traffic: it keys on the session id — not the body hash, which would
    scatter refines of one session across backends whenever the new support
    set differs — and honors the session-table binding the adapt/refine
    responses taught. Plain adapts (no ``refine`` field) keep the body-hash
    key byte-identically."""
    g = Gateway(["http://a", "http://b"], health_interval_s=30.0)
    for backend in g.backends:
        g.observe(backend, True, "ok")
    sid = "sess-42"
    refine_body = json.dumps(
        {"refine": True, "session_id": sid, "x_support": [1], "y_support": [2]}
    ).encode()
    key, preferred = g.affinity_key("/adapt", refine_body)
    assert key == sid and preferred is None  # rendezvous fallback pre-learn
    # a DIFFERENT support payload for the same session -> the SAME key
    other_body = json.dumps(
        {"refine": True, "session_id": sid, "x_support": [9, 9], "y_support": [0]}
    ).encode()
    assert g.affinity_key("/adapt", other_body)[0] == sid
    # refine responses ride /adapt and teach/update the binding the same
    # way adapt responses do (adaptation_id IS the session id)
    g._learn_from_response(
        "/adapt",
        json.dumps({"adaptation_id": sid, "refined": True}).encode(),
        g.backends[1],
    )
    assert g.affinity_key("/adapt", refine_body)[1] is g.backends[1]
    # the session's predicts share the learned binding
    predict_body = json.dumps({"adaptation_id": sid, "x_query": [1]}).encode()
    assert g.affinity_key("/predict", predict_body)[1] is g.backends[1]
    # plain adapt: body-hash key, no session preference — unchanged
    plain_body = json.dumps({"x_support": [1], "y_support": [2]}).encode()
    key, preferred = g.affinity_key("/adapt", plain_body)
    assert key != sid and preferred is None
    g.close()


def test_gateway_admission_control_sheds_429():
    s0, u0 = _spawn_fake("s0", lambda n, p: (200, {"probs": [[1.0]]}, None),
                         delay_s=0.6)
    g = Gateway([u0], health_interval_s=30.0, pass_threshold=1, max_inflight=1)
    g.observe(g.backends[0], True, "ok")
    server = make_gateway_server(g, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    outcomes = []
    lock = threading.Lock()

    def one():
        import urllib.error

        try:
            code, _, headers = _post(
                base + "/predict", {"adaptation_id": "a", "x_query": [1]},
                timeout=30,
            )
            row = (code, None)
        except urllib.error.HTTPError as exc:
            row = (exc.code, exc.headers.get("Retry-After"))
        with lock:
            outcomes.append(row)

    threads = [threading.Thread(target=one) for _ in range(3)]
    threads[0].start()
    time.sleep(0.15)
    for t in threads[1:]:
        t.start()
    for t in threads:
        t.join(timeout=30)
    try:
        codes = sorted(c for c, _ in outcomes)
        assert 200 in codes and 429 in codes, outcomes
        assert all(ra is not None for c, ra in outcomes if c == 429)
        assert g.metrics()["admission_shed"] >= 1
    finally:
        server.shutdown()
        server.server_close()
        g.close()
        s0.shutdown()
        s0.server_close()


def test_gateway_and_rolling_restart_scripts_are_import_light():
    """The CLIs must run on a gateway-only host with NO jax installed. The
    contract's single source of truth is now graftlint GL213: the scripts
    carry `# graftlint: import-light` markers and the rule walks their
    transitive module-scope import closure (this replaced three duplicated
    subprocess __import__-guard probes; tests/test_graftlint.py pins that
    each script still carries the marker)."""
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join("scripts", "lint.py"),
            "--json",
            "--rule",
            "GL213",
            "scripts",
            "howtotrainyourmamlpytorch_tpu",
            "tools",
        ],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["counts"] == {}, payload["findings"]


# ---------------------------------------------------------------------------
# HttpFrontend (loadgen --url / BENCH_GATEWAY): wire -> outcome taxonomy
# ---------------------------------------------------------------------------


def test_http_frontend_outcome_mapping_and_per_backend_counts():
    from howtotrainyourmamlpytorch_tpu.observability.slo import HttpFrontend

    state = {"mode": "ok"}

    def script(name, path):
        if state["mode"] == "shed":
            return 503, {"error": "shed"}, {"Retry-After": "3"}
        if state["mode"] == "unknown":
            return 404, {"error": "unknown id"}, None
        if path == "/adapt":
            return 200, {"adaptation_id": "aid-1"}, None
        return 200, {"probs": [[0.25, 0.75]]}, None

    srv, url = _spawn_fake("s0", script)
    # fake gateway header so per-backend tallies have a name
    orig = srv.script

    def with_header(name, path):
        code, body, headers = orig(name, path)
        return code, body, {**(headers or {}), "X-Gateway-Backend": "b0"}

    srv.script = with_header
    frontend = HttpFrontend(url, timeout_s=10)
    try:
        info = frontend.adapt(np.zeros((2, 2)), np.zeros(2, np.int32))
        assert info["adaptation_id"] == "aid-1"
        probs = frontend.predict("aid-1", np.zeros((1, 2)))
        assert probs.shape == (1, 2)
        state["mode"] = "shed"
        with pytest.raises(ServiceUnavailableError) as err:
            frontend.predict("aid-1", np.zeros((1, 2)))
        assert err.value.status == 503 and err.value.retry_after_s == 3.0
        state["mode"] = "unknown"
        with pytest.raises(UnknownAdaptationError):
            frontend.predict("aid-1", np.zeros((1, 2)))
        counts = frontend.per_backend()["b0"]
        assert counts["ok"] == 2 and counts["shed"] == 1 and counts["unknown_id"] == 1
        assert frontend.breaker.snapshot() == {}  # run_load contract
    finally:
        srv.shutdown()
        srv.server_close()


# ---------------------------------------------------------------------------
# drain semantics + healthz status schema (tiny real engine)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def drain_setup():
    cfg = Config(
        num_classes_per_set=5,
        num_samples_per_class=2,
        num_target_samples=3,
        batch_size=2,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        serving=ServingConfig(
            support_buckets=[16], query_buckets=[16], max_batch_size=1
        ),
    )
    system = MAMLSystem(
        cfg, model=build_vgg(_IMG, 5, num_stages=2, cnn_num_filters=4)
    )
    engine = AdaptationEngine(system, system.init_train_state())
    # settle the compiles outside every timed drain window
    b = synthetic_batch(1, 5, 2, 3, _IMG, seed=1)
    fw = engine.adapt(b["x_support"][0], b["y_support"][0])
    engine.predict(fw, b["x_target"][0].reshape((-1,) + _IMG))
    yield cfg, engine


def _episode(seed):
    b = synthetic_batch(1, 5, 2, 3, _IMG, seed=seed)
    return (
        b["x_support"][0],
        b["y_support"][0],
        b["x_target"][0].reshape((-1,) + _IMG),
    )


def test_drain_completes_inflight_and_queued_then_refuses(drain_setup):
    """SIGTERM semantics at the unit level: requests in flight (and queued
    behind them) when the drain begins ALL complete; a request arriving
    after drain starts is refused 503 + Retry-After; healthz flips to
    'draining' (503 class) for the gateway to see."""
    cfg, engine = drain_setup
    inj = FaultInjector.from_specs(
        ["serving.dispatch=delay:delay_s=0.25,p=1.0"], include_env=False
    )
    old = engine.injector
    engine.injector = inj
    frontend = ServingFrontend(engine)
    try:
        x_s, y_s, x_q = _episode(5)
        info = frontend.adapt(x_s, y_s)
        results = []
        lock = threading.Lock()

        def one():
            try:
                p = frontend.predict(info["adaptation_id"], x_q)
                row = ("ok", np.asarray(p))
            except Exception as exc:  # noqa: BLE001 — the row is the verdict
                row = (type(exc).__name__, None)
            with lock:
                results.append(row)

        threads = [threading.Thread(target=one) for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.1)  # in flight: first mid-dispatch, rest queued
        drain_box = {}

        def drain():
            drain_box.update(frontend.begin_drain(reason="unit"))

        drainer = threading.Thread(target=drain)
        drainer.start()
        time.sleep(0.05)
        assert frontend.healthz()["status"] == "draining"
        # a NEW request during the drain: 503 + Retry-After, never queued
        with pytest.raises(ServiceUnavailableError) as err:
            frontend.predict(info["adaptation_id"], x_q)
        assert err.value.status == 503 and err.value.retry_after_s > 0
        for t in threads:
            t.join(timeout=60)
        drainer.join(timeout=60)
        assert [r[0] for r in results] == ["ok", "ok", "ok"], results
        assert drain_box["ok"] is True and drain_box["deadline_exceeded"] is False
        assert drain_exit_code(drain_box) == exit_codes.OK
    finally:
        engine.injector = old
        frontend.close()


def test_drain_deadline_expiry_takes_the_registered_rc(drain_setup):
    """A drain that cannot finish inside the deadline reports
    deadline_exceeded and maps to exit_codes.DRAIN_DEADLINE — a distinct,
    registered rc (not 0, not the wedge 76)."""
    cfg, engine = drain_setup
    inj = FaultInjector.from_specs(
        ["serving.dispatch=delay:delay_s=1.2,p=1.0"], include_env=False
    )
    old = engine.injector
    engine.injector = inj
    frontend = ServingFrontend(engine)
    try:
        x_s, y_s, x_q = _episode(6)
        info_box = {}

        def adapt_slow():
            try:
                info_box["info"] = frontend.adapt(x_s, y_s)
            except Exception as exc:  # noqa: BLE001
                info_box["error"] = exc

        t = threading.Thread(target=adapt_slow)
        t.start()
        time.sleep(0.2)
        info = frontend.begin_drain(deadline_s=0.2, reason="unit")
        assert info["deadline_exceeded"] is True and info["ok"] is False
        rc = drain_exit_code(info)
        assert rc == exit_codes.DRAIN_DEADLINE == 77
        assert rc not in (exit_codes.OK, exit_codes.WEDGED, exit_codes.PREEMPTED)
        t.join(timeout=60)
    finally:
        engine.injector = old
        frontend.close()


def test_healthz_status_schema_pinned(drain_setup):
    """Satellite fix: drain / warm / degraded are DISTINCT machine-readable
    status values (one field, four values) — a gateway switches on
    healthz["status"] alone, so the schema is pinned here."""
    cfg, engine = drain_setup
    frontend = ServingFrontend(engine, replicas=2)
    observed = set()
    try:
        observed.add(frontend.healthz()["status"])
        # degraded: a dead replica (fleet partially down, still routable)
        frontend.kill_replica(0, reason="schema-pin")
        health = frontend.healthz()
        assert health["status"] == "degraded" and health["routable"] == 1
        observed.add(health["status"])
        # warming: the AOT prewarm still compiling
        with frontend._prewarm_lock:
            saved = frontend._prewarm
            frontend._prewarm = {"status": "warming"}
        observed.add(frontend.healthz()["status"])
        with frontend._prewarm_lock:
            frontend._prewarm = saved
        # draining beats everything: the replica is leaving
        frontend.begin_drain(reason="schema-pin")
        observed.add(frontend.healthz()["status"])
        # THE pin: one field, exactly these four machine-readable values —
        # each reachable, none conflated with another
        assert observed == {"ok", "degraded", "warming", "draining"}
    finally:
        frontend.close()


def test_session_store_verdicts_corrupt_stale_foreign(tmp_path, drain_setup):
    """Rehydration safety matrix: digest-verified load; corrupt file ->
    quarantined *.corrupt, never served; TTL-lapsed -> ignored+removed;
    other-checkpoint fingerprint -> left untouched; loaded -> consumed."""
    cfg, engine = drain_setup
    store = SessionStore(str(tmp_path / "sessions"))
    x_s, y_s, _ = _episode(7)
    tree = engine.adapt(x_s, y_s)
    store.spill("d" * 64, tree, "fp-A", age_s=0.0, ttl_s=600.0)
    store.spill("e" * 64, tree, "fp-A", age_s=599.0, ttl_s=600.0,
                wall_clock=lambda: time.time() - 10.0)  # already lapsed
    store.spill("f" * 64, tree, "fp-B", age_s=0.0, ttl_s=600.0)
    corrupt_path = store.spill("a" * 64, tree, "fp-A", age_s=0.0, ttl_s=600.0)
    with open(corrupt_path, "r+b") as f:
        f.seek(100)
        f.write(b"\x00" * 32)
    assert store.pending() == 4
    entries, stats = store.load_all("fp-A", template=engine.state.params)
    assert stats == {"loaded": 1, "stale": 1, "corrupt": 1, "foreign": 1}
    assert [d for d, _, _, _, _ in entries] == ["d" * 64]
    # pre-registry spill (no strategy kwarg) reads back as the default
    assert entries[0][3] == "maml++"
    # lived_s reports the TTL budget already consumed (cache age at spill +
    # wall time on disk) — what the rehydrating cache back-dates with
    assert entries[0][2] >= 0.0
    # the loaded tree round-trips bit-identically
    np.testing.assert_array_equal(
        np.asarray(next(iter(jax_leaves(tree)))),
        np.asarray(next(iter(jax_leaves(entries[0][1])))),
    )
    # corrupt quarantined (visible), foreign left, loaded+stale gone
    names = sorted(os.listdir(store.root))
    assert any(n.endswith(".corrupt") for n in names)
    assert any(("f" * 64) in n for n in names)
    assert store.pending() == 1  # only the foreign one still parked


def jax_leaves(tree):
    import jax

    return jax.tree.leaves(tree)


# ---------------------------------------------------------------------------
# obs_top: gateway frame
# ---------------------------------------------------------------------------


def _load_obs_top():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "obs_top_gwtest", os.path.join(REPO, "scripts", "obs_top.py")
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def test_obs_top_renders_gateway_membership_per_backend():
    obs_top = _load_obs_top()
    metrics = {
        "gateway": True,
        "requests": 40,
        "retries": 3,
        "admission_shed": 1,
        "no_backend": 0,
        "sessions": 5,
        "backends_in": 1,
        "uptime_s": 12.5,
        "access_log": {"lines": 40},
        "backends": [
            {"backend": "b0", "url": "http://h0:8100", "state": "in",
             "last_status": "ok", "flaps": 0, "routed": 30, "retried_away": 0},
            {"backend": "b1", "url": "http://h1:8100", "state": "out",
             "last_status": "draining", "flaps": 1, "routed": 10,
             "retried_away": 3},
        ],
    }
    prev = obs_top.gateway_frame(metrics, None, 2.0)
    assert prev["source"] == "gateway" and prev["qps"] is None
    frame = obs_top.gateway_frame({**metrics, "requests": 50}, prev, 2.0)
    assert frame["qps"] == 5.0
    assert frame["backends_in"] == 1 and frame["backends_total"] == 2
    rendered = obs_top.render(frame)
    assert "b0" in rendered and "IN" in rendered
    assert "b1" in rendered and "OUT" in rendered and "draining" in rendered


def test_obs_top_auto_detects_supervisor_and_renders_controller_frame():
    """ISSUE 18: a fleet supervisor's /metrics (the {"supervisor": true}
    marker) renders the CONTROLLER frame — per-backend slot state, the last
    decision + its reason, and the live cooldown timers."""
    obs_top = _load_obs_top()
    metrics = {
        "supervisor": True,
        "uptime_s": 33.1,
        "gateway_url": "http://127.0.0.1:9000",
        "running": 2,
        "target": 2,
        "min_backends": 1,
        "max_backends": 4,
        "streaks": {"up": 1, "down": 0},
        "cooldowns": {"up_remaining_s": 7.5, "down_remaining_s": 0.0},
        "signals": {"queue_depth_max": 9.0, "shed_rate": 0.0},
        "last_decision": {
            "ts": 123.0, "event": "scale_up", "component": "supervisor",
            "slot": 1, "reason": "queue_depth_max 9.0 > 8.0",
            "outcome": "up", "settle_s": 4.2,
        },
        "pending_overrides": ["serving.support_buckets=[2]"],
        "counters": {"ticks": 10, "scale_ups": 1, "scale_downs": 0,
                     "quarantines": 0},
        "intent": None,
        "slots": [
            {"slot": 0, "url": "http://127.0.0.1:9101", "state": "up",
             "pid": 100, "crashes_in_window": 0, "next_spawn_in_s": None},
            {"slot": 1, "url": "http://127.0.0.1:9102", "state": "up",
             "pid": 101, "crashes_in_window": 0, "next_spawn_in_s": None},
            {"slot": 2, "url": "http://127.0.0.1:9103", "state": "quarantined",
             "pid": None, "crashes_in_window": 3, "next_spawn_in_s": 12.5},
        ],
    }

    class _Args:
        url = "http://sup"
        timeout_s = 1.0
        interval = 2.0
        run_dir = None

    # build_frame auto-detects the marker (monkeypatch the fetch)
    obs_top._fetch_metrics = lambda url, timeout_s: metrics
    prev = obs_top.build_frame(_Args, None)
    assert prev["source"] == "supervisor" and prev["ticks_per_s"] is None
    frame = obs_top.build_frame(
        _Args, {**prev, "_ticks": 4}
    )
    assert frame["ticks_per_s"] == 3.0  # (10 - 4) / 2.0
    rendered = obs_top.render(frame)
    assert "2/2" in rendered and "min 1 max 4" in rendered
    assert "scale_up" in rendered and "queue_depth_max 9.0 > 8.0" in rendered
    assert "cooldown up 7.5s" in rendered
    assert "QUARANTINED" in rendered and "crashes 3" in rendered
    assert "next_spawn_in 12.5s" in rendered
    assert "prewarm  serving.support_buckets=[2]" in rendered
    # the JSON surface drops the _-prefixed delta bookkeeping
    public = {k: v for k, v in frame.items() if not k.startswith("_")}
    assert "_ticks" not in public and public["running"] == 2


# ---------------------------------------------------------------------------
# THE cross-process drills (subprocess gateway + real serve backends)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fleet_template(tmp_path_factory):
    """One toy serving run dir (config + init-state checkpoint) shared by
    every cross-process drill — each drill copies it byte-for-byte, so the
    whole module pays for ONE checkpoint build."""
    root = tmp_path_factory.mktemp("fleet_template")
    return make_serving_run_dir(str(root), "template")


def _run_drill(kind, tmp_path, fleet_template):
    violations = _run_gateway_episode(
        Episode(kind=kind, mode="gateway", subprocess=True),
        work_dir=str(tmp_path),
        template_run=fleet_template,
    )
    assert violations == [], violations


def test_cross_process_kill9_availability_and_honest_failover(
    tmp_path, fleet_template
):
    """ACCEPTANCE: kill -9 one of two real backends mid-flight — the
    gateway routes around it within the hysteresis window (availability
    never reaches zero), the displaced session 404s then re-adapts to
    bit-identical predictions (never stale), membership flap in the
    gateway's events.jsonl."""
    _run_drill("gateway-kill9-backend", tmp_path, fleet_template)


def test_cross_process_sigterm_drain_spill_rehydrate(tmp_path, fleet_template):
    """ACCEPTANCE: SIGTERM a real backend mid-load — zero dropped in-flight
    requests, clean rc 0, sessions spilled digest-verified, and the
    respawned replica serves the OLD adaptation id from its rehydrated
    cache (post-restart cache hit, bit-identical probs)."""
    _run_drill("gateway-drain-rehydrate", tmp_path, fleet_template)


def test_cross_process_rolling_restart_under_load(tmp_path, fleet_template):
    """ACCEPTANCE: scripts/rolling_restart.py drains + respawns both
    backends one at a time under live load: the fleet keeps serving, both
    come back warm (healthz-gated), and every non-200 the driver saw
    resolves to a gateway access line by request id."""
    _run_drill("gateway-rolling-restart", tmp_path, fleet_template)


def test_cross_process_refined_session_survives_drain_and_gateway_kill(
    tmp_path, fleet_template
):
    """ACCEPTANCE (ISSUE 17): a REFINED session survives a SIGTERM drain +
    rehydrate (post-restart predictions bit-identical to the refined
    weights, the next refine CONTINUES the lineage at refine_count 2) AND a
    kill -9 of the gateway in front of it (a fresh gateway serves the same
    session bit-identically and the lineage keeps counting) — never a
    silently-reset session."""
    _run_drill("serve-refine-across-drain", tmp_path, fleet_template)


def test_cross_process_fleet_surge_autoscale_cycle(tmp_path, fleet_template):
    """ACCEPTANCE (ISSUE 18): scripts/fleet_serve.py closes the scaling
    loop against a REAL fleet — surging load on a slowed backend breaches
    the queue signal, the supervisor spawns the pre-provisioned second slot
    (healthz-gated, gateway admits it), the SLO recovers, and when the load
    stops the added backend is gracefully drained (rc 0 observed in the
    scale_down event) back to min_backends. Zero dropped connections across
    the cycle and a refined session's lineage intact (refine_count 2)."""
    _run_drill("fleet-surge", tmp_path, fleet_template)


def test_cross_process_fleet_crashloop_and_supervisor_kill9(
    tmp_path, fleet_template
):
    """ACCEPTANCE (ISSUE 18): crash-safe control. A die-on-spawn backend
    walks the bounded backoff ladder (increasing backoffs in events.jsonl)
    into quarantine — never respawned hot, fleet still routable. Then a
    supervisor kill -9'd mid-spawn (intent + pid write-ahead journaled,
    warm gate unfinished) restarts, adopts the live fleet from
    fleet_state.json, and settles the interrupted spawn with the SAME pid —
    no double-spawn, no orphan — until the gateway admits the backend."""
    _run_drill("fleet-crashloop", tmp_path, fleet_template)
