"""Circuit breaker for the serving engine's device dispatch.

When the device path starts failing repeatedly (wedged tunnel, poisoned
compile cache, OOM loop), every queued request burns a full dispatch attempt
and a deadline before failing — the breaker converts that into an immediate,
cheap 503 the client can back off on, and probes the device again after a
cooldown.

States (classic three-state breaker):

- ``closed``: all calls pass; ``failure_threshold`` *consecutive* failures
  trip it open.
- ``open``: calls are rejected without dispatching; after ``cooldown_s``
  (measured on the injectable clock) the next ``allow()`` moves to half-open.
- ``half_open``: up to ``half_open_probes`` calls pass as probes. Any probe
  failure re-opens (fresh cooldown); once ``half_open_probes`` probes succeed
  the breaker closes.

Thread-safe; the clock is injectable so tests walk the whole state machine
with zero real waiting.
"""

import threading
import time
from typing import Any, Callable, Dict

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitBreaker:
    def __init__(
        self,
        failure_threshold: int = 5,
        cooldown_s: float = 10.0,
        half_open_probes: int = 1,
        clock: Callable[[], float] = time.monotonic,
    ):
        if failure_threshold < 1:
            raise ValueError(f"failure_threshold must be >= 1, got {failure_threshold}")
        if half_open_probes < 1:
            raise ValueError(f"half_open_probes must be >= 1, got {half_open_probes}")
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self.half_open_probes = int(half_open_probes)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probes_allowed = 0
        self._probes_succeeded = 0
        # lifetime counters for /metrics
        self.opens = 0
        self.rejections = 0
        self.failures = 0
        self.successes = 0

    # ------------------------------------------------------------------

    def _trip_locked(self) -> None:
        self._state = OPEN
        self._opened_at = self._clock()
        self._consecutive_failures = 0
        self._probes_allowed = 0
        self._probes_succeeded = 0
        self.opens += 1

    def allow(self) -> bool:
        """May a call proceed right now? Rejections are counted. A True from
        half-open consumes one probe slot — the caller MUST follow up with
        ``record_success``/``record_failure``."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at >= self.cooldown_s:
                    self._state = HALF_OPEN
                    self._probes_allowed = 0
                    self._probes_succeeded = 0
                else:
                    self.rejections += 1
                    return False
            # half-open: bounded probe slots
            if self._probes_allowed < self.half_open_probes:
                self._probes_allowed += 1
                return True
            self.rejections += 1
            return False

    def release_probe(self) -> None:
        """Give back a half-open probe slot whose call never produced a
        verdict (shed before dispatch, or timed out with the outcome
        unknown). Without this, an unresolved probe would permanently consume
        the slot and wedge the breaker in half_open — rejecting all traffic
        forever even after the device recovers."""
        with self._lock:
            if self._state == HALF_OPEN and self._probes_allowed > 0:
                self._probes_allowed -= 1

    def record_success(self) -> None:
        with self._lock:
            self.successes += 1
            if self._state == HALF_OPEN:
                self._probes_succeeded += 1
                if self._probes_succeeded >= self.half_open_probes:
                    self._state = CLOSED
                    self._consecutive_failures = 0
            else:
                self._consecutive_failures = 0

    def record_failure(self) -> None:
        with self._lock:
            self.failures += 1
            if self._state == HALF_OPEN:
                self._trip_locked()  # a failed probe re-opens with fresh cooldown
                return
            self._consecutive_failures += 1
            if self._state == CLOSED and self._consecutive_failures >= self.failure_threshold:
                self._trip_locked()

    # ------------------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            # surface the lazily-entered half-open so /healthz reads right
            # even before the first post-cooldown call arrives
            if (
                self._state == OPEN
                and self._clock() - self._opened_at >= self.cooldown_s
            ):
                return HALF_OPEN
            return self._state

    def snapshot(self) -> Dict[str, Any]:
        state = self.state
        with self._lock:
            return {
                "state": state,
                "opens": self.opens,
                "rejections": self.rejections,
                "failures": self.failures,
                "successes": self.successes,
                "consecutive_failures": self._consecutive_failures,
            }
