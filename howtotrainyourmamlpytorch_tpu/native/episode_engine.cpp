// Native episode-assembly engine — the host-side data hot path in C++.
//
// The reference assembles episodes in Python inside 4 forked DataLoader
// workers (reference data.py:486-532,584-590): per image, a PIL/numpy load,
// an np.rot90, and a copy into the episode tensor. Here the whole meta-batch
// is assembled by one native call over the packed in-RAM image cache:
// gather + rotation-k augmentation + optional mean/std normalization + pack
// into the [B, n_way, n_samples, H, W, C] batch layout, parallelized over
// (episode, class) jobs with a std::thread pool.
//
// Episode *randomness* stays in Python (numpy RandomState, call-for-call
// parity with the reference's seed discipline); this engine is purely the
// data-movement half: it receives the drawn global image indices and
// per-class rotation counts.
//
// Build: g++ -O3 -shared -fPIC -std=c++17 -pthread (see __init__.py).

#include <atomic>
#include <cstdint>
#include <thread>
#include <vector>

namespace {

// Copy one H x W x C image with np.rot90(arr, k, axes=(0,1)) semantics.
// Requires H == W when k is odd (both supported datasets are square).
inline void copy_rot90(const float* src, float* dst, int64_t H, int64_t W,
                       int64_t C, int k) {
  if (k == 0) {
    const int64_t n = H * W * C;
    for (int64_t i = 0; i < n; ++i) dst[i] = src[i];
    return;
  }
  for (int64_t i = 0; i < H; ++i) {
    for (int64_t j = 0; j < W; ++j) {
      // out[i, j] = in[si, sj]; np.rot90 rotates counter-clockwise k times:
      // k=1: out[i, j] = in[j, W-1-i]  (square H==W for odd k)
      // k=2: out[i, j] = in[H-1-i, W-1-j]
      // k=3: out[i, j] = in[H-1-j, i]
      int64_t si, sj;
      switch (k & 3) {
        case 1: si = j;          sj = W - 1 - i; break;
        case 2: si = H - 1 - i;  sj = W - 1 - j; break;
        case 3: si = H - 1 - j;  sj = i;         break;
        default: si = i;         sj = j;         break;
      }
      const float* s = src + (si * W + sj) * C;
      float* d = dst + (i * W + j) * C;
      for (int64_t c = 0; c < C; ++c) d[c] = s[c];
    }
  }
}

// Divides (not multiply-by-reciprocal) so results are bit-exact with the
// numpy fallback's (arr - mean) / std.
inline void normalize(float* img, int64_t HW, int64_t C, const float* mean,
                      const float* std_dev) {
  for (int64_t p = 0; p < HW; ++p) {
    float* px = img + p * C;
    for (int64_t c = 0; c < C; ++c) px[c] = (px[c] - mean[c]) / std_dev[c];
  }
}

}  // namespace

extern "C" {

// cache:     [total_images, H, W, C] float32, all images of one split packed
// image_idx: [B, n_way, n_samples] int64 global indices into cache
// rot_k:     [B, n_way] int32 rotation counts (0..3); pass zeros to disable
// out:       [B, n_way, n_samples, H, W, C] float32
// mean/std:  length-C channel statistics; has_norm=0 skips normalization
// Returns 0 on success, 1 on invalid arguments (odd rotation of non-square).
int assemble_episodes(const float* cache, const int64_t* image_idx,
                      const int32_t* rot_k, float* out, int64_t B,
                      int64_t n_way, int64_t n_samples, int64_t H, int64_t W,
                      int64_t C, const float* mean, const float* std_dev,
                      int has_norm, int num_threads) {
  if (H != W) {
    const int64_t n_jobs_check = B * n_way;
    for (int64_t i = 0; i < n_jobs_check; ++i)
      if (rot_k[i] & 1) return 1;  // odd rot90 of non-square image
  }
  const int64_t img_elems = H * W * C;
  const int64_t n_jobs = B * n_way;  // one job = one class slot of one episode
  std::atomic<int64_t> next{0};

  auto worker = [&]() {
    for (;;) {
      const int64_t job = next.fetch_add(1, std::memory_order_relaxed);
      if (job >= n_jobs) return;
      const int k = rot_k[job] & 3;
      const int64_t* idx = image_idx + job * n_samples;
      float* dst = out + job * n_samples * img_elems;
      for (int64_t s = 0; s < n_samples; ++s) {
        const float* src = cache + idx[s] * img_elems;
        copy_rot90(src, dst + s * img_elems, H, W, C, k);
        if (has_norm)
          normalize(dst + s * img_elems, H * W, C, mean, std_dev);
      }
    }
  };

  int n_threads = num_threads > 0 ? num_threads : 1;
  if (n_threads > n_jobs) n_threads = static_cast<int>(n_jobs);
  if (n_threads <= 1) {
    worker();
  } else {
    std::vector<std::thread> threads;
    threads.reserve(n_threads);
    for (int t = 0; t < n_threads; ++t) threads.emplace_back(worker);
    for (auto& th : threads) th.join();
  }
  return 0;
}

}  // extern "C"
