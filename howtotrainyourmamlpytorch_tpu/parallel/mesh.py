"""Device mesh + sharding layer (SPMD over ICI).

The reference is hard-coded single-GPU (``train_maml_system.py:23``); its
``num_of_gpus`` key only inflates the DataLoader batch (``data.py:589``).
Here data parallelism is native: the meta-batch (task axis) is sharded over
the ``dp`` mesh axis with ``NamedSharding``; because the meta-objective is a
``vmap`` + mean over that axis, XLA partitions the whole second-order program
across chips and inserts the meta-gradient ``psum`` automatically — the
collectives ride ICI, no NCCL-style bespoke layer (SURVEY.md §2.11, §5.8).
``mp`` is exposed for parameter sharding of larger backbones (2D data x model
mesh API).

Multi-host: ``initialize_distributed`` wraps ``jax.distributed.initialize`` so
the same program scales over DCN across hosts; on a single host it is a no-op.
"""

import os
from typing import Any, Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import ParallelConfig

DATA_AXIS = "dp"
MODEL_AXIS = "mp"


def make_mesh(parallel: Optional[ParallelConfig] = None, devices=None) -> Mesh:
    parallel = parallel or ParallelConfig()
    devices = list(devices if devices is not None else jax.devices())
    mp = max(parallel.mp, 1)
    dp = parallel.dp if parallel.dp and parallel.dp > 0 else len(devices) // mp
    if dp * mp > len(devices):
        raise ValueError(f"mesh {dp}x{mp} needs {dp * mp} devices, have {len(devices)}")
    grid = np.array(devices[: dp * mp]).reshape(dp, mp)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def requested_mesh_shape(parallel: Optional[ParallelConfig], n_devices: int):
    """The ``(dp, mp)`` the config demands given ``n_devices`` visible
    (``dp=-1`` auto-sizes to the visible devices, so it can never be
    infeasible by itself; an explicit dp can)."""
    parallel = parallel or ParallelConfig()
    mp = max(parallel.mp, 1)
    dp = parallel.dp if parallel.dp and parallel.dp > 0 else max(n_devices // mp, 1)
    return dp, mp


def degraded_mesh_plan(
    parallel: Optional[ParallelConfig], n_devices: int, global_batch_size: int
):
    """Shrink plan for resuming on fewer devices than ``ParallelConfig``
    demands — the device-loss half of the wedge-and-shrink failure class: a
    TPU slice comes back from maintenance with a dead chip and the demanded
    ``dp x mp`` no longer fits, which used to kill the run at ``make_mesh``.

    Returns ``None`` when the demanded shape fits, else ``(dp, mp)`` of the
    largest feasible degraded mesh: ``mp`` is kept if it still fits (model
    sharding is a memory requirement, not a preference), else collapsed to 1;
    ``dp`` drops to the largest value that (a) fits beside ``mp`` and (b)
    divides the global meta-batch, so the existing divisibility contract
    holds without reshaping the batch. ``(1, 1)`` means single-device
    fallback (the caller skips the mesh entirely). Training continues at
    reduced throughput; the math is unchanged — the meta-objective is a mean
    over the task axis, and resharding only re-places the same arrays."""
    dp_req, mp_req = requested_mesh_shape(parallel, n_devices)
    if dp_req * mp_req <= n_devices:
        return None
    mp = mp_req if mp_req <= n_devices else 1
    budget = max(n_devices // mp, 1)
    dp = 1
    for cand in range(min(budget, dp_req), 0, -1):
        if global_batch_size % cand == 0:
            dp = cand
            break
    return dp, mp


def grow_mesh_plan(
    parallel: Optional[ParallelConfig],
    n_devices: int,
    global_batch_size: int,
    current,
):
    """Grow plan — the inverse of :func:`degraded_mesh_plan`: the run is on a
    degraded ``current = (dp, mp)`` mesh and more devices are visible again
    (slice back from maintenance, resume on a healed host). Returns the
    largest feasible ``(dp, mp)`` — the full requested shape when it fits,
    else the best degraded shape the visible devices allow — or ``None``
    when that is no improvement over ``current``. "Improvement" is strictly
    more devices in use: the plan never trades dp for mp sideways, so a
    grow-back is always a pure capacity gain and the shrink/grow pair can
    never oscillate between equal-sized shapes. The math is unchanged in
    both directions — resharding only re-places the same arrays (see
    ``degraded_mesh_plan``); the cost of a grow is one re-placement plus the
    recompiles for the new mesh."""
    cur_dp, cur_mp = current
    plan = degraded_mesh_plan(parallel, n_devices, global_batch_size)
    best = requested_mesh_shape(parallel, n_devices) if plan is None else plan
    if best[0] * best[1] <= cur_dp * cur_mp:
        return None
    return best


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Tasks of the meta-batch sharded over dp; everything else replicated."""
    return NamedSharding(mesh, P(DATA_AXIS))


def chunk_sharding(mesh: Mesh) -> NamedSharding:
    """Multi-step dispatch chunks ``[K, B, ...]`` (train_steps_per_dispatch):
    scan axis replicated, meta-batch axis 1 sharded over dp."""
    return NamedSharding(mesh, P(None, DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch: Dict[str, Any], mesh: Mesh) -> Dict[str, Any]:
    sharding = batch_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def replicate(tree, mesh: Mesh):
    sharding = replicated(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)


def _param_spec(shape, mp: int, tp_convs: bool = False, leaf_name=None) -> P:
    """Tensor-parallel spec for one parameter leaf: *dense (2-D) kernels* —
    leaves named ``'w'``, the layer-zoo kernel convention — shard their
    output-features axis (column-parallel ``P(None, 'mp')``) when it divides
    ``mp``; with ``tp_convs`` HWIO conv kernels (4-D ``'w'`` leaves) shard
    their output-channel axis the same way; everything else is replicated.
    Both branches key off the name, not shape alone (ADVICE r4 for the 4-D
    branch, ADVICE r5 #1 for the 2-D one): a future 2-D non-kernel parameter
    — a learned per-(step, tensor) hparam table, a class-embedding matrix
    whose trailing axis happens to divide mp — must not be silently
    mp-sharded by its shape.

    Why exactly this layout (verified on the 8-device CPU mesh):
    - on the NATIVE conv path, conv-kernel channel sharding is rejected by
      XLA's SPMD partitioner for this program family — the vmap over tasks
      becomes a batch-grouped convolution and ``spmd_partitioner``
      hard-crashes in ``convolution_handler.cc`` ("Check failed:
      new_input_batch_size % new_output_batch_size == 0"). ``tp_convs``
      therefore requires the patches-GEMM conv implementation
      (``Config.conv_via_patches``, auto-enabled), whose dot_general
      contraction GSPMD partitions with standard matmul collectives:
      output-channel (column) sharded kernels produce channel-sharded
      activations, and the next layer's contraction over its sharded input
      channels partial-sums against the matching kernel rows (row-parallel),
      Megatron-style — all inserted automatically;
    - row-parallel (input-axis) dense sharding is unsafe whenever the conv
      stack pools down to 1x1 spatial (the 28x28 4-stage default): the
      flatten reshape is then channel-aligned, the sharding propagates back
      into the conv output channels, and on the native path the same
      partitioner crash fires;
    - without ``tp_convs``, column-parallel on the head alone keeps all
      activations replicated until the logits, so the conv stack never sees
      a sharded operand.
    The conv kernels here are <=150KB, so conv TP buys memory/FLOP spread
    only as backbones widen; the machinery is exercised end-to-end either
    way (tests/test_parallel.py, __graft_entry__.dryrun_multichip)."""
    if (
        leaf_name == "w"
        and len(shape) == 2
        and shape[1] >= mp
        and shape[1] % mp == 0
    ):
        return P(None, MODEL_AXIS)
    if (
        tp_convs
        and leaf_name == "w"
        and len(shape) == 4
        and shape[3] >= mp
        and shape[3] % mp == 0
    ):
        return P(None, None, None, MODEL_AXIS)
    return P()


def train_state_shardings(state, mesh: Mesh, tp_convs: bool = False):
    """NamedSharding pytree for a ``TrainState``: model parameters and their
    optimizer-moment mirrors are tensor-parallel over ``mp`` (SURVEY.md §2.11
    TP row — pjit param sharding specs on conv/linear weights); everything
    else (BN stats, per-tensor inner hparams, scalars) is replicated. With
    ``mp == 1`` every leaf is replicated — identical to :func:`replicate`."""
    mp = mesh.shape.get(MODEL_AXIS, 1)
    rep = NamedSharding(mesh, P())
    if mp == 1:
        return jax.tree.map(lambda _: rep, state)

    def param_sharding(path, leaf):
        leaf_name = getattr(path[-1], "key", None) if path else None
        return NamedSharding(
            mesh, _param_spec(tuple(leaf.shape), mp, tp_convs, leaf_name)
        )

    def opt_spec(path, leaf):
        # the outer optimizer's moment trees (adam mu/nu) mirror the
        # trainables dict {'params': ..., 'hparams': ...}: shard the 'params'
        # mirrors exactly like the params; inner hparams are per-tensor
        # scalars — nothing to shard
        keys = {getattr(k, "key", None) for k in path}
        return param_sharding(path, leaf) if "params" in keys else rep

    return type(state)(
        params=jax.tree_util.tree_map_with_path(param_sharding, state.params),
        bn_state=jax.tree.map(lambda _: rep, state.bn_state),
        inner_hparams=jax.tree.map(lambda _: rep, state.inner_hparams),
        opt_state=jax.tree_util.tree_map_with_path(opt_spec, state.opt_state),
        step=rep,
    )


def shard_train_state(state, mesh: Mesh, tp_convs: bool = False):
    """Place a TrainState pytree onto the mesh with tensor-parallel parameter
    shardings (replicates everything when ``mp == 1``)."""
    return jax.tree.map(
        jax.device_put, state, train_state_shardings(state, mesh, tp_convs)
    )


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host (DCN) bring-up. On a single host this is a no-op; on a pod
    slice, call once per host before building the mesh (jax multi-host runtime
    handles the DCN transport — SURVEY.md §5.8)."""
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if num_processes is None or num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def host_shard_bounds(batch_size: int, process_index: int, process_count: int):
    """[start, stop) of the global meta-batch this host materializes. The
    global batch divides evenly over hosts (enforced), so every host builds
    ``batch_size // process_count`` episodes of each global batch."""
    if batch_size % process_count != 0:
        raise ValueError(
            f"global batch_size {batch_size} not divisible by "
            f"process_count {process_count}"
        )
    per_host = batch_size // process_count
    return process_index * per_host, (process_index + 1) * per_host


def global_batch_from_local(local_batch, mesh: Mesh, sharding: Optional[NamedSharding] = None):
    """Assemble per-host local episode arrays into global jax.Arrays sharded
    over the mesh's ``dp`` axis (multi-host SPMD input path: each host feeds
    only its shard; ``jax.make_array_from_process_local_data`` stitches the
    global view over DCN — SURVEY.md §5.8). Pass a cached ``sharding`` on hot
    paths to preserve sharding-identity caching downstream."""
    if sharding is None:
        sharding = batch_sharding(mesh)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sharding, np.asarray(x)),
        local_batch,
    )
