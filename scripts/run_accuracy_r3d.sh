#!/bin/bash
# Round-3 accuracy matrix, part D (runs after part C): the axes part C
# doesn't cover — the inner-optimizer ablation (the fork's whole point:
# model x inner-opt ablated independently), the third backbone family
# (densenet-8), and two more seeds of the headline 5w1s config for a true
# 3-seed mean like the reference's notebook aggregation.
# Reference anchors (BASELINE.md): 5.1 vgg+Adam 99.62+-0.08,
# 5.1 densenet-8+SGD 99.54+-0.33, 5.1 vgg+SGD 99.62+-0.08.
# Note: seed overrides must come AFTER the COMMON block's seed=0 (last
# occurrence wins in the config override parser).
mkdir -p /root/repo/exps
exec "$(dirname "$0")/sweep.sh" \
  "omniglot.5.1.vgg.adam.s0       num_classes_per_set=5 num_samples_per_class=1 net=vgg inner_optim=adam" \
  "omniglot.5.1.densenet-8.gd.s0  num_classes_per_set=5 num_samples_per_class=1 net=densenet-8" \
  "omniglot.5.1.vgg.gd.s1         num_classes_per_set=5 num_samples_per_class=1 net=vgg seed=1 train_seed=1" \
  "omniglot.5.1.vgg.gd.s2         num_classes_per_set=5 num_samples_per_class=1 net=vgg seed=2 train_seed=2"
