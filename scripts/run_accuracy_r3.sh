#!/bin/bash
# Round-3 accuracy matrix (VERDICT r2 item 1): the reference's published
# Omniglot configs (BASELINE.md / nbs cells 9-11), full 150-epoch budget,
# seed 0, serial on the attached TPU chip via the watchdogged harness.
# Reference anchors: vgg+SGD 5w1s 99.62+-0.08, 5w5s 99.86+-0.02,
# 20w1s 97.21+-0.11, 20w5s 99.13+-0.13; resnet-4+SGD 5w1s 99.91+-0.05.
exec "$(dirname "$0")/sweep.sh" \
  "omniglot.5.1.vgg.gd.s0      num_classes_per_set=5  num_samples_per_class=1 net=vgg" \
  "omniglot.20.1.vgg.gd.s0     num_classes_per_set=20 num_samples_per_class=1 net=vgg" \
  "omniglot.5.5.vgg.gd.s0      num_classes_per_set=5  num_samples_per_class=5 net=vgg" \
  "omniglot.20.5.vgg.gd.s0     num_classes_per_set=20 num_samples_per_class=5 net=vgg" \
  "omniglot.5.1.resnet-4.gd.s0 num_classes_per_set=5  num_samples_per_class=1 net=resnet-4"
