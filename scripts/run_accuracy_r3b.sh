#!/bin/bash
# Round-3 accuracy matrix, part B: the configs remaining after 5w1s
# completed (99.57% test) and 20w1s was parked for diagnosis. Thin wrapper
# over the watchdogged harness (scripts/sweep.sh).
exec "$(dirname "$0")/sweep.sh" \
  "omniglot.5.5.vgg.gd.s0      num_classes_per_set=5  num_samples_per_class=5 net=vgg" \
  "omniglot.5.1.resnet-4.gd.s0 num_classes_per_set=5  num_samples_per_class=1 net=resnet-4" \
  "omniglot.20.5.vgg.gd.s0     num_classes_per_set=20 num_samples_per_class=5 net=vgg"
