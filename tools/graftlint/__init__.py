"""graftlint — a JAX/TPU hazard linter for this repo's program families.

Rule families (catalog + rationale: docs/STATIC_ANALYSIS.md):

- **GL1xx jax hazards** — tracer concretization / Python control flow in
  jit-reachable code, host syncs on designated hot paths, nondeterminism
  sources, donation-after-use.
- **GL2xx concurrency** — unguarded read-modify-writes in threaded classes,
  untimed blocking waits.
- **GL3xx contracts** — exit-code registry discipline, OPERATIONS.md rc
  table drift, fault-seam name registry.

Entry points: ``scripts/lint.py`` (CLI; rc=0 clean / 1 findings / 2 usage)
and the library API here. Stdlib-``ast`` only — no dependencies, so the
tier-1 self-gate (tests/test_graftlint.py) runs anywhere the suite runs.
"""

from .engine import (  # noqa: F401
    RULES,
    Finding,
    Module,
    Project,
    Rule,
    load_project,
    register,
    report_human,
    report_json,
    run_lint,
)
from . import rules_concurrency, rules_contracts, rules_jax  # noqa: F401  (register)
