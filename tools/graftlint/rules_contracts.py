"""GL3xx — contract rules: cross-checks against single sources of truth.

GL301  bare exit-code literal at an exit site (the registry is
       ``howtotrainyourmamlpytorch_tpu/exit_codes.py``)
GL302  docs/OPERATIONS.md rc table drifted from the registry
GL303  fault-seam name not in ``resilience/faults.py::SEAMS``

All three read their source of truth STATICALLY (ast / text) — the linter
never imports the code it lints, so it runs on broken trees and costs no
jax import.
"""

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .engine import Finding, Module, Project, Rule, call_name, const_int, register

EXIT_CODES_SUFFIX = "exit_codes.py"
FAULTS_SUFFIX = "resilience/faults.py"

#: codes whose bare use is fine everywhere (generic CLI conventions / HTTP
#: statuses used in wire-level assertions)
_GENERIC_CODES = {0, 1, 2, 503, 504}


def _module_int_consts(mod: Module) -> Dict[str, int]:
    """Module-level ``NAME = <int>`` assignments."""
    out: Dict[str, int] = {}
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            value = const_int(node.value)
            if isinstance(target, ast.Name) and value is not None:
                out[target.id] = value
    return out


def _registry_codes(project: Project) -> Optional[Set[int]]:
    """The special process exit codes from the registry module, or None when
    the lint roots don't include one (rule inactive)."""
    mod = project.module_by_suffix(EXIT_CODES_SUFFIX)
    if mod is None:
        return None
    consts = _module_int_consts(mod)
    return {v for v in consts.values() if v not in _GENERIC_CODES}


@register
class BareExitCodeLiteral(Rule):
    id = "GL301"
    title = "bare exit-code literal instead of the exit_codes registry"

    _EXIT_CALLS = {"SystemExit", "exit", "sys.exit", "os._exit", "_exit"}

    def check_module(self, module: Module, project: Project) -> Iterable[Finding]:
        if module.rel.endswith(EXIT_CODES_SUFFIX):
            return []
        special = _registry_codes(project)
        if not special:
            return []
        findings: List[Finding] = []

        def flag(node: ast.AST, code: int, where: str) -> None:
            findings.append(
                Finding(
                    self.id,
                    module.rel,
                    node.lineno,
                    node.col_offset,
                    f"bare exit code {code} {where} — import it from the "
                    "exit_codes registry so the contract can't drift",
                )
            )

        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                name = call_name(node) or ""
                if name in self._EXIT_CALLS or name.split(".")[-1] in (
                    "exit",
                    "_exit",
                    "SystemExit",
                ):
                    for arg in node.args:
                        code = const_int(arg)
                        if code in special:
                            flag(arg, code, f"passed to {name}()")
                for kw in node.keywords:
                    if kw.arg and kw.arg.endswith("exit_code"):
                        code = const_int(kw.value)
                        if code in special:
                            flag(kw.value, code, f"as {kw.arg}=")
            elif isinstance(node, ast.Compare):
                for comp in node.comparators:
                    if isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                        lits = [const_int(e) for e in comp.elts]
                        hits = [c for c in lits if c in special]
                        if hits and any(
                            isinstance(op, (ast.In, ast.NotIn)) for op in node.ops
                        ):
                            flag(
                                comp,
                                hits[0],
                                "in a membership test against literal codes",
                            )
            elif isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name
            ):
                if node.target.id.endswith("exit_code") and node.value is not None:
                    code = const_int(node.value)
                    if code in special:
                        flag(node.value, code, f"as default of {node.target.id}")
        return findings


@register
class OperationsRcTableDrift(Rule):
    id = "GL302"
    title = "docs/OPERATIONS.md rc table drifted from the registry"

    _ROW_RE = re.compile(r"^\|\s*(\d+)\s*\|")

    def _registry_table(self, mod: Module) -> Optional[Dict[int, str]]:
        """Statically evaluate ``TRAIN_PROCESS_RCS = {NAME: "...", ...}``."""
        consts = _module_int_consts(mod)
        for node in mod.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "TRAIN_PROCESS_RCS"
                and isinstance(node.value, ast.Dict)
            ):
                table: Dict[int, str] = {}
                for k, v in zip(node.value.keys, node.value.values):
                    code = (
                        consts.get(k.id) if isinstance(k, ast.Name) else const_int(k)
                    )
                    if code is None:
                        return None
                    table[code] = (
                        v.value if isinstance(v, ast.Constant) else ""
                    )
                return table
        return None

    def check_project(self, project: Project) -> Iterable[Finding]:
        reg_mod = project.module_by_suffix(EXIT_CODES_SUFFIX)
        if reg_mod is None:
            return []
        table = self._registry_table(reg_mod)
        if table is None:
            return [
                Finding(
                    self.id,
                    reg_mod.rel,
                    1,
                    0,
                    "exit_codes.py has no statically-readable "
                    "TRAIN_PROCESS_RCS dict",
                )
            ]
        doc_path = os.path.join(project.repo_root, "docs", "OPERATIONS.md")
        if not os.path.exists(doc_path):
            return []
        with open(doc_path, encoding="utf-8") as f:
            doc_lines = f.read().splitlines()
        # scan ONLY the exit-code table: from the marker line to the end of
        # its contiguous `|`-row block — other numeric-first-column tables
        # elsewhere in the doc (wire sequences, HTTP statuses) are not rc
        # contracts and must not trip the gate
        doc_codes: Dict[int, int] = {}  # rc -> line number
        in_section = False
        in_table = False
        for i, line in enumerate(doc_lines, start=1):
            stripped = line.strip()
            if not in_section:
                if "exit-code table" in stripped.lower():
                    in_section = True
                continue
            if stripped.startswith("|"):
                in_table = True
                m = self._ROW_RE.match(stripped)
                if m:
                    doc_codes[int(m.group(1))] = i
            elif in_table:
                break  # first non-row line after the table ends the scan
        findings: List[Finding] = []
        rel_doc = os.path.relpath(doc_path, os.getcwd())
        for code in sorted(set(table) - set(doc_codes)):
            findings.append(
                Finding(
                    self.id,
                    rel_doc,
                    1,
                    0,
                    f"rc {code} ({table[code]}) is in the exit_codes registry "
                    "but missing from the OPERATIONS.md exit-code table",
                )
            )
        for code in sorted(set(doc_codes) - set(table)):
            findings.append(
                Finding(
                    self.id,
                    rel_doc,
                    doc_codes[code],
                    0,
                    f"rc {code} appears in the OPERATIONS.md exit-code table "
                    "but not in the exit_codes registry — add it there first",
                )
            )
        # the TPU wait-gate codes live in prose, not the table; they must
        # still be documented
        consts = _module_int_consts(reg_mod)
        text = "\n".join(doc_lines)
        for name in ("TPU_WAIT_DEADLINE", "TPU_WAIT_WEDGED"):
            if name not in consts:
                continue
            # bounded so '65' inside '0.65', '1650' or '6.5e4' cannot satisfy
            # the documentation requirement (\b alone still matches after a
            # decimal point)
            if not re.search(rf"(?<![\d.]){consts[name]}(?!\d)", text):
                findings.append(
                    Finding(
                        self.id,
                        rel_doc,
                        1,
                        0,
                        f"registry code {name}={consts[name]} is not "
                        "mentioned anywhere in OPERATIONS.md",
                    )
                )
        return findings


@register
class UnknownFaultSeam(Rule):
    id = "GL303"
    title = "fault-seam name not in the faults.py registry"

    _SPEC_RE = re.compile(r"^([A-Za-z_][\w]*(?:\.[\w]+)+)=([a-z][a-z-]*)(?=[:;,]|$)")

    def _seams_and_kinds(
        self, project: Project
    ) -> Optional[Tuple[Set[str], Set[str], Module]]:
        mod = project.module_by_suffix(FAULTS_SUFFIX)
        if mod is None:
            return None
        seams: Set[str] = set()
        kinds: Set[str] = set()
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
                if isinstance(target, ast.Name) and isinstance(
                    node.value, (ast.Tuple, ast.List)
                ):
                    values = {
                        e.value
                        for e in node.value.elts
                        if isinstance(e, ast.Constant) and isinstance(e.value, str)
                    }
                    if target.id == "SEAMS":
                        seams = values
                    elif target.id == "KINDS":
                        kinds = values
        if not seams or not kinds:
            return None
        return seams, kinds, mod

    def check_project(self, project: Project) -> Iterable[Finding]:
        resolved = self._seams_and_kinds(project)
        if resolved is None:
            # only a finding if a faults.py exists but lacks the registry
            mod = project.module_by_suffix(FAULTS_SUFFIX)
            if mod is not None:
                return [
                    Finding(
                        self.id,
                        mod.rel,
                        1,
                        0,
                        "resilience/faults.py defines no statically-readable "
                        "SEAMS tuple — the seam registry is the single "
                        "source of truth GL303 checks against",
                    )
                ]
            return []
        seams, kinds, _ = resolved
        findings: List[Finding] = []
        for mod in project.modules:
            for node in ast.walk(mod.tree):
                # .fire("site") / .fire_bytes("site", ...)
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("fire", "fire_bytes")
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    site = node.args[0].value
                    if site not in seams:
                        findings.append(
                            Finding(
                                self.id,
                                mod.rel,
                                node.lineno,
                                node.col_offset,
                                f"fault seam {site!r} is not in "
                                "resilience/faults.py::SEAMS — register it "
                                "there (it is the drillable-surface "
                                "inventory) or fix the typo",
                            )
                        )
                # fault-spec strings: "<site>=<kind>[...]" (plain or the
                # literal head of an f-string)
                text = None
                if isinstance(node, ast.Constant) and isinstance(node.value, str):
                    text = node.value
                elif isinstance(node, ast.JoinedStr) and node.values:
                    head = node.values[0]
                    if isinstance(head, ast.Constant) and isinstance(
                        head.value, str
                    ):
                        text = head.value
                if text:
                    for chunk in re.split(r"[;\n]", text):
                        m = self._SPEC_RE.match(chunk.strip())
                        if m and m.group(2) in kinds and m.group(1) not in seams:
                            findings.append(
                                Finding(
                                    self.id,
                                    mod.rel,
                                    node.lineno,
                                    node.col_offset,
                                    f"fault spec names unknown seam "
                                    f"{m.group(1)!r} (kinds matched "
                                    f"{m.group(2)!r}) — not in "
                                    "resilience/faults.py::SEAMS",
                                )
                            )
        return findings
