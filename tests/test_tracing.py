"""Request-scoped tracing (ISSUE 10): traceparent plumbing, flow-linked
span chains across threads, the structured access log, the Prometheus
exposition, trace_merge over a 2-process toy fleet run, and the obs_top
console contract."""

import importlib.util
import json
import os
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.config import Config, ObservabilityConfig, ServingConfig
from howtotrainyourmamlpytorch_tpu.core import MAMLSystem
from howtotrainyourmamlpytorch_tpu.data.synthetic import synthetic_batch
from howtotrainyourmamlpytorch_tpu.models import build_vgg
from howtotrainyourmamlpytorch_tpu.observability import slo
from howtotrainyourmamlpytorch_tpu.observability.context import (
    AccessLog,
    RequestContext,
    format_traceparent,
    new_request_context,
    parse_traceparent,
)
from howtotrainyourmamlpytorch_tpu.observability.metrics import (
    MetricsRegistry,
    prometheus_text,
)
from howtotrainyourmamlpytorch_tpu.observability.trace import (
    SpanTracer,
    load_and_validate_trace,
    validate_chrome_trace,
)
from howtotrainyourmamlpytorch_tpu.serving import (
    AdaptationEngine,
    ServingFrontend,
    make_http_server,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_IMG = (28, 28, 1)


# ---------------------------------------------------------------------------
# context: traceparent + minting
# ---------------------------------------------------------------------------


def test_traceparent_round_trip_and_minting():
    ctx = new_request_context()
    assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    assert ctx.parent_id is None and ctx.sampled

    # a downstream hop adopts our trace id and parents on our span id
    header = format_traceparent(ctx)
    child = parse_traceparent(header)
    assert child.trace_id == ctx.trace_id
    assert child.parent_id == ctx.span_id
    assert child.span_id != ctx.span_id  # each hop mints its own
    assert child.sampled

    unsampled = parse_traceparent(f"00-{'a' * 32}-{'b' * 16}-00")
    assert unsampled.sampled is False and unsampled.trace_id == "a" * 32


@pytest.mark.parametrize(
    "header",
    [
        None,
        "",
        "garbage",
        "00-short-deadbeef-01",
        f"00-{'0' * 32}-{'b' * 16}-01",  # all-zero trace id is invalid
        f"ff-{'a' * 32}-{'b' * 16}-01",  # unknown version
    ],
)
def test_bad_traceparent_mints_fresh(header):
    ctx = parse_traceparent(header)
    assert ctx.parent_id is None and len(ctx.trace_id) == 32


def test_access_log_sampling_deterministic_and_failure_bypass(tmp_path):
    log = AccessLog(str(tmp_path), sample=0.5, wall_clock=lambda: 123.0)
    # deterministic on the id: leading bits decide, identically everywhere
    low = RequestContext(trace_id="00000000" + "0" * 23 + "1", span_id="a" * 16)
    high = RequestContext(trace_id="ffffffff" + "0" * 24, span_id="a" * 16)
    assert log.record(low, "adapt", "ok", 200, 0.01)
    assert not log.record(high, "adapt", "ok", 200, 0.01)
    # ... but a FAILURE on the sampled-out id is always logged
    assert log.record(high, "adapt", "shed", 503, 0.01)
    stats = log.stats()
    assert stats["lines"] == 2 and stats["sampled_out"] == 1
    lines = [json.loads(l) for l in open(log.path)]
    assert [l["outcome"] for l in lines] == ["ok", "shed"]
    assert lines[1]["trace_id"] == high.trace_id and lines[1]["status"] == 503
    log.close()


# ---------------------------------------------------------------------------
# tracer: flow events + real pid + validator pairing
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def test_flow_events_exported_and_validated(tmp_path):
    clock = FakeClock()
    tracer = SpanTracer(capacity=16, clock=clock, wall_clock=lambda: 1000.0)
    tid_a, tid_b = "a" * 32, "b" * 32
    # two requests, one flush (the batched case): two s, one flush span
    # carrying two t flows, one dispatch span carrying two f flows
    with tracer.span("serve.flush", flows=[(tid_a, "t"), (tid_b, "t")]):
        clock.advance(0.1)
        with tracer.span("dispatch", flows=[(tid_a, "f"), (tid_b, "f")]):
            clock.advance(0.2)
    with tracer.span("serve.adapt", flows=[(tid_a, "s")]):
        clock.advance(0.05)
    with tracer.span("serve.adapt", flows=[(tid_b, "s")]):
        clock.advance(0.05)
    trace = tracer.to_chrome_trace()
    assert validate_chrome_trace(trace) == []
    flows = [e for e in trace["traceEvents"] if e["ph"] in ("s", "t", "f")]
    by_role = {}
    for e in flows:
        assert e["name"] == "request" and e["cat"] == "request"
        by_role.setdefault(e["ph"], set()).add(e["id"])
    assert by_role == {"s": {tid_a, tid_b}, "t": {tid_a, tid_b}, "f": {tid_a, tid_b}}
    # binding: t/f anchor to their ENCLOSING slice
    assert all("bp" in e for e in flows if e["ph"] in ("t", "f"))
    # real pid on every event + the merge anchor in otherData
    assert all(e["pid"] == os.getpid() for e in trace["traceEvents"])
    assert trace["otherData"]["epoch_unix"] == 1000.0
    path = str(tmp_path / "t.json")
    tracer.export(path)
    assert load_and_validate_trace(path) == []


def test_validator_flow_pairing():
    def tr(events):
        return {"traceEvents": events}

    # a finish whose flow never started is the torn-arc signature
    bad = tr([{"name": "request", "cat": "request", "ph": "f", "id": "x",
               "ts": 0, "pid": 1, "tid": 0, "bp": "e"}])
    assert any("no start" in p for p in validate_chrome_trace(bad))
    # id-less flow events are unbindable
    bad = tr([{"name": "request", "cat": "request", "ph": "s",
               "ts": 0, "pid": 1, "tid": 0}])
    assert any("without an id" in p for p in validate_chrome_trace(bad))
    # a start with no finish is NOT a violation: that is what a cache hit /
    # shed request legitimately looks like
    ok = tr([{"name": "request", "cat": "request", "ph": "s", "id": "x",
              "ts": 0, "pid": 1, "tid": 0}])
    assert validate_chrome_trace(ok) == []
    # order-independence: the ring orders by span completion, so f-then-s
    # within one export is the NORMAL nesting order
    ok = tr([
        {"name": "request", "cat": "request", "ph": "f", "id": "y",
         "ts": 5, "pid": 1, "tid": 0, "bp": "e"},
        {"name": "request", "cat": "request", "ph": "s", "id": "y",
         "ts": 0, "pid": 1, "tid": 1},
    ])
    assert validate_chrome_trace(ok) == []


# ---------------------------------------------------------------------------
# prometheus exposition
# ---------------------------------------------------------------------------


def test_prometheus_text_schema_pin():
    reg = MetricsRegistry()
    reg.inc("serving.events.shed", 3)
    reg.set_gauge("flops_per_step", 1.5e9)
    reg.set_gauge("breaker_state", "open")  # non-numeric: JSON-only
    for v in (0.01, 0.02, 0.03):
        reg.observe("phase.settle", v)
    text = prometheus_text(reg)
    lines = text.splitlines()
    assert "# TYPE htymp_serving_events_shed_total counter" in lines
    assert "htymp_serving_events_shed_total 3" in lines
    assert "# TYPE htymp_flops_per_step gauge" in lines
    assert "htymp_flops_per_step 1500000000.0" in lines
    assert "# TYPE htymp_phase_settle summary" in lines
    assert 'htymp_phase_settle{quantile="0.5"} 0.02' in lines
    assert "htymp_phase_settle_count 3" in lines
    assert any(l.startswith("htymp_phase_settle_sum ") for l in lines)
    assert not any("breaker_state" in l for l in lines)
    # every sample line is exposition-format: name{labels}? value
    import re

    sample = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [-+0-9.eE]+$"
    )
    for line in lines:
        if line.startswith("#"):
            continue
        assert sample.match(line), line


# ---------------------------------------------------------------------------
# serving e2e: HTTP -> access log -> flow-linked trace
# ---------------------------------------------------------------------------


def _tiny_cfg(**obs_kwargs):
    return Config(
        num_classes_per_set=5,
        num_samples_per_class=2,
        num_target_samples=3,
        batch_size=2,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        serving=ServingConfig(
            support_buckets=[16], query_buckets=[16], max_batch_size=4,
            batch_deadline_ms=30.0,
        ),
        observability=ObservabilityConfig(**obs_kwargs),
    )


@pytest.fixture(scope="module")
def tiny_system_state():
    cfg = _tiny_cfg()
    system = MAMLSystem(
        cfg, model=build_vgg(_IMG, 5, num_stages=2, cnn_num_filters=4)
    )
    return cfg, system, system.init_train_state()


def _episode(seed):
    b = synthetic_batch(1, 5, 2, 3, _IMG, seed=seed)
    return (
        b["x_support"][0],
        b["y_support"][0],
        b["x_target"][0].reshape((-1,) + _IMG),
    )


def test_http_request_to_access_line_to_flow_trace(tmp_path, tiny_system_state):
    """THE acceptance chain: one HTTP request -> an access.jsonl line whose
    trace id appears as a linked flow (s at the HTTP span, t at the flush,
    f at the engine dispatch) in the exported trace, with the timing
    breakdown in the response body and the id echoed in X-Request-Id."""
    cfg, system, state = tiny_system_state
    frontend = ServingFrontend(
        AdaptationEngine(system, state), access_log_dir=str(tmp_path)
    )
    server = make_http_server(frontend, "127.0.0.1", 0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    upstream = new_request_context()
    try:
        x_s, y_s, x_q = _episode(5)
        req = urllib.request.Request(
            base + "/adapt",
            data=json.dumps(
                {"x_support": x_s.tolist(), "y_support": y_s.tolist()}
            ).encode(),
            headers={
                "Content-Type": "application/json",
                "traceparent": format_traceparent(upstream),
            },
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            out = json.loads(resp.read())
            rid = resp.headers["X-Request-Id"]
            echoed = resp.headers["traceparent"]
        # the caller's trace id is adopted, echoed, and parented
        assert rid == upstream.trace_id
        assert out["trace_id"] == upstream.trace_id
        assert echoed.split("-")[1] == upstream.trace_id
        timing = out["timing"]
        assert timing["total_ms"] > 0
        assert timing["queue_wait_ms"] is not None
        assert timing["dispatch_ms"] is not None
        # predict rides a fresh server-minted id
        req2 = urllib.request.Request(
            base + "/predict",
            data=json.dumps(
                {"adaptation_id": out["adaptation_id"], "x_query": x_q.tolist()}
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req2, timeout=120) as resp:
            out2 = json.loads(resp.read())
            rid2 = resp.headers["X-Request-Id"]
        assert out2["trace_id"] == rid2 and rid2 != rid
        assert out2["timing"]["total_ms"] > 0

        # access.jsonl: one line per request, fields per the runbook table
        lines = [json.loads(l) for l in open(os.path.join(str(tmp_path), "access.jsonl"))]
        by_id = {l["trace_id"]: l for l in lines}
        assert set(by_id) == {rid, rid2}
        adapt_line = by_id[rid]
        assert adapt_line["verb"] == "adapt" and adapt_line["outcome"] == "ok"
        assert adapt_line["status"] == 200
        assert adapt_line["parent_id"] == upstream.span_id
        assert adapt_line["bucket"] == 16
        assert adapt_line["flush_batch"] == 1
        assert adapt_line["cache_hit"] is False
        assert adapt_line["queue_wait_ms"] is not None
        assert adapt_line["dispatch_ms"] is not None
        assert adapt_line["breaker"] == "closed"

        # the exported trace links the journey: s (HTTP span) -> t (flush)
        # -> f (dispatch) for BOTH request ids, and validates
        trace = frontend.hub.tracer.to_chrome_trace()
        assert validate_chrome_trace(trace) == []
        roles = {}
        for e in trace["traceEvents"]:
            if e["ph"] in ("s", "t", "f"):
                roles.setdefault(e["id"], set()).add(e["ph"])
        assert roles[rid] == {"s", "t", "f"}
        assert roles[rid2] == {"s", "t", "f"}
        # /metrics surfaces the access log and the prom exposition parses
        with urllib.request.urlopen(base + "/metrics", timeout=30) as resp:
            metrics = json.loads(resp.read())
        assert metrics["access_log"]["lines"] == 2
        with urllib.request.urlopen(base + "/metrics?format=prom", timeout=30) as resp:
            assert resp.headers["Content-Type"].startswith("text/plain")
            prom = resp.read().decode()
        assert "htymp_serving_latency_adapt_count 1" in prom.splitlines()
    finally:
        server.shutdown()
        server.server_close()
        frontend.close()
        thread.join(timeout=5)


def test_batched_flush_two_requests_one_flush_two_flows(tiny_system_state, tmp_path):
    """Two concurrent same-bucket predicts coalesce into ONE flush span
    that carries BOTH trace flows — the continuous-batching attribution:
    each access line records flush_batch=2 and the same dispatch cost."""
    cfg, system, state = tiny_system_state
    frontend = ServingFrontend(
        AdaptationEngine(system, state), access_log_dir=str(tmp_path)
    )
    try:
        x_s, y_s, x_q = _episode(7)
        info = frontend.adapt(x_s, y_s)
        frontend.predict(info["adaptation_id"], x_q)  # warm the program

        ctxs = [new_request_context(), new_request_context()]
        barrier = threading.Barrier(2)

        def hit(ctx):
            barrier.wait(5.0)
            frontend.predict(info["adaptation_id"], x_q, ctx=ctx)

        threads = [threading.Thread(target=hit, args=(c,)) for c in ctxs]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30.0)

        assert {c.flush_batch for c in ctxs} == {2}
        assert all(c.queue_wait_s is not None for c in ctxs)
        assert ctxs[0].dispatch_s == ctxs[1].dispatch_s  # one shared dispatch
        recs = frontend.hub.tracer.records()
        both = {c.trace_id for c in ctxs}
        flush_flows = [
            set(fid for fid, role in (r["flows"] or ()))
            for r in recs
            if r["name"] == "serve.flush.predict" and r["flows"]
        ]
        assert both in flush_flows  # ONE flush span carries both flows
        lines = [json.loads(l) for l in open(os.path.join(str(tmp_path), "access.jsonl"))]
        batched = [l for l in lines if l["trace_id"] in both]
        assert len(batched) == 2
        assert all(l["flush_batch"] == 2 for l in batched)
    finally:
        frontend.close()


def test_disabled_observability_is_zero_file_and_header_free(tmp_path, tiny_system_state):
    """Observability off: no access.jsonl, no trace ids minted, no
    X-Request-Id / timing keys on the wire — the request path is
    bit-identical to the un-instrumented build."""
    _, system, state = tiny_system_state
    cfg = _tiny_cfg(enabled=False)
    system_off = MAMLSystem(
        cfg, model=build_vgg(_IMG, 5, num_stages=2, cnn_num_filters=4)
    )
    off_dir = str(tmp_path / "off")
    frontend = ServingFrontend(
        AdaptationEngine(system_off, state), access_log_dir=off_dir
    )
    server = make_http_server(frontend, "127.0.0.1", 0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        x_s, y_s, x_q = _episode(9)
        req = urllib.request.Request(
            base + "/adapt",
            data=json.dumps(
                {"x_support": x_s.tolist(), "y_support": y_s.tolist()}
            ).encode(),
            headers={"Content-Type": "application/json",
                     "traceparent": f"00-{'a' * 32}-{'b' * 16}-01"},
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            out = json.loads(resp.read())
            assert resp.headers.get("X-Request-Id") is None
        assert "trace_id" not in out and "timing" not in out
        assert frontend.access_log is None
        assert not os.path.exists(off_dir)
        assert frontend.hub.tracer.records() == []
    finally:
        server.shutdown()
        server.server_close()
        frontend.close()
        thread.join(timeout=5)


def test_access_log_disabled_by_config_knob(tmp_path, tiny_system_state):
    """observability.access_log=false keeps tracing but writes no file."""
    _, _, state = tiny_system_state
    cfg = _tiny_cfg(access_log=False)
    system = MAMLSystem(
        cfg, model=build_vgg(_IMG, 5, num_stages=2, cnn_num_filters=4)
    )
    log_dir = str(tmp_path / "noaccess")
    frontend = ServingFrontend(
        AdaptationEngine(system, state), access_log_dir=log_dir
    )
    try:
        x_s, y_s, _ = _episode(11)
        out = frontend.adapt(x_s, y_s)
        assert "trace_id" in out  # tracing still on
        assert frontend.access_log is None
        assert not os.path.exists(log_dir)
    finally:
        frontend.close()


# ---------------------------------------------------------------------------
# SLO report: failing stairs name their worst request ids
# ---------------------------------------------------------------------------


def test_slo_report_failing_stair_names_worst_ids(tmp_path):
    schedule = [
        slo.Request(t=0.1 * i, kind="predict", episode_seed=i, n_query=5,
                    stair=i // 4)
        for i in range(8)
    ]
    rows = [
        {"stair": 0, "kind": "predict", "outcome": "ok",
         "latency_ms": 10.0 + i, "trace_id": f"fast{i:028x}"}
        for i in range(4)
    ] + [
        {"stair": 1, "kind": "predict", "outcome": "ok" if i else "deadline",
         "latency_ms": 5000.0 - i * 1000, "trace_id": f"slow{i:028x}"}
        for i in range(4)
    ]
    access_path = str(tmp_path / "access.jsonl")
    with open(access_path, "w") as f:
        f.write(json.dumps({
            "trace_id": "slow" + "0" * 28, "queue_wait_ms": 4900.0,
            "dispatch_ms": 50.0, "flush_batch": 3, "bucket": 16,
        }) + "\n")
    report = slo.slo_report(
        schedule,
        {"rows": rows, "breaker_trips": 0, "wall_s": 1.0},
        stairs_rps=[4, 8],
        duration_s=2.0,
        seed=0,
        slo_p99_ms=100.0,
        max_shed_rate=0.05,
        worst_k=2,
        access_log_path=access_path,
    )
    s0, s1 = report["stairs"]
    assert s0["slo_met"] and "worst_requests" not in s0
    assert not s1["slo_met"]
    worst = s1["worst_requests"]
    assert len(worst) == 2
    # ranked by latency; the deadline miss leads and joins its access line
    assert worst[0]["trace_id"] == "slow" + "0" * 28
    assert worst[0]["outcome"] == "deadline"
    assert worst[0]["queue_wait_ms"] == 4900.0 and worst[0]["flush_batch"] == 3
    assert report["access_log"]["lines"] == 1


def test_run_load_mints_trace_ids_and_drives_ctx_frontends():
    """run_load stamps a loadgen-minted trace id on every outcome row, and
    still drives ctx-less frontend doubles (the back-compat seam)."""

    class _Breaker:
        def snapshot(self):
            return {"opens": 0}

    class Plain:  # no ctx parameter anywhere
        breaker = _Breaker()

        def adapt(self, x, y):
            return {"adaptation_id": "a"}

        def predict(self, aid, xq):
            return np.zeros((1, 5))

    schedule = [
        slo.Request(t=0.0, kind="adapt", episode_seed=1, n_query=5, stair=0),
        slo.Request(t=0.01, kind="predict", episode_seed=2, n_query=5, stair=0),
    ]
    run = slo.run_load(
        Plain(), schedule, lambda s: (None, None), lambda s, n: None,
        warm_adaptations=1, result_grace_s=5.0,
    )
    assert len(run["rows"]) == 2
    assert all(len(r["trace_id"]) == 32 for r in run["rows"])
    assert len({r["trace_id"] for r in run["rows"]}) == 2


# ---------------------------------------------------------------------------
# trace_merge: 2-process toy fleet run -> one validated Perfetto file
# ---------------------------------------------------------------------------

_CHILD_SCRIPT = r"""
import importlib.util, json, os, sys, time
repo, run_dir, trace_id, t_base = sys.argv[1], sys.argv[2], sys.argv[3], float(sys.argv[4])
spec = importlib.util.spec_from_file_location(
    "t", os.path.join(repo, "howtotrainyourmamlpytorch_tpu", "observability", "trace.py"))
trace = importlib.util.module_from_spec(spec)
spec.loader.exec_module(trace)
clock = [0.0]
tracer = trace.SpanTracer(capacity=64, clock=lambda: clock[0], wall_clock=lambda: t_base)
with tracer.span("serve.adapt", flows=[(trace_id, "s")], trace=trace_id):
    clock[0] += 0.01
    with tracer.span("serve.flush.adapt", flows=[(trace_id, "t")]):
        clock[0] += 0.02
        with tracer.span("serve.adapt_dispatch", flows=[(trace_id, "f")]):
            clock[0] += 0.03
logs = os.path.join(run_dir, "logs")
os.makedirs(logs, exist_ok=True)
tracer.export(os.path.join(logs, "trace.json"))
with open(os.path.join(logs, "access.jsonl"), "w") as f:
    f.write(json.dumps({"ts": t_base + 0.06, "trace_id": trace_id, "verb": "adapt",
                        "outcome": "ok", "status": 200, "total_ms": 60.0}) + "\n")
print(os.getpid())
"""


def test_trace_merge_round_trip_two_process_toy_fleet(tmp_path):
    """Two real processes (distinct pids) each export a flow-linked trace +
    access log; a fleet_events.jsonl rides along. trace_merge emits ONE
    file that load_and_validate_trace accepts, with each process on its
    own real-pid track, both flows intact, and access/fleet rows as
    events."""
    root = tmp_path / "fleet"
    ids = ["c" * 32, "d" * 32]
    pids = []
    for i, tid in enumerate(ids):
        run_dir = root / f"cell{i}"
        run_dir.mkdir(parents=True)
        proc = subprocess.run(
            [sys.executable, "-c", _CHILD_SCRIPT, REPO_ROOT, str(run_dir),
             tid, str(1000.0 + i)],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, proc.stderr
        pids.append(int(proc.stdout.strip()))
    assert pids[0] != pids[1]
    with open(root / "fleet_events.jsonl", "w") as f:
        f.write(json.dumps({"ts": 1000.5, "event": "cell_launch", "cell": "cell0"}) + "\n")
        f.write(json.dumps({"ts": 1001.5, "event": "cell_done", "cell": "cell1", "rc": 0}) + "\n")

    out = str(tmp_path / "merged.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts", "trace_merge.py"),
         "--root", str(root), "--out", out],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr + proc.stdout
    summary = json.loads(proc.stdout)
    assert summary["ok"] and summary["traces"] == 2
    assert summary["access_lines"] == 2 and summary["fleet_events"] == 2

    assert load_and_validate_trace(out) == []
    with open(out) as f:
        merged = json.load(f)
    events = merged["traceEvents"]
    # each child keeps its REAL pid track, named after its run dir
    x_pids = {e["pid"] for e in events if e["ph"] == "X" and e.get("cat") == "host"}
    assert x_pids == set(pids)
    names = {
        e["args"]["name"]
        for e in events
        if e["ph"] == "M" and e["name"] == "process_name"
    }
    assert {"cell0", "cell1", "fleet"} <= names
    # both flows survive the merge, one s/t/f arc each
    roles = {}
    for e in events:
        if e["ph"] in ("s", "t", "f"):
            roles.setdefault(e["id"], set()).add(e["ph"])
    assert roles == {ids[0]: {"s", "t", "f"}, ids[1]: {"s", "t", "f"}}
    # wall-clock alignment: cell1's anchor is 1s after cell0's
    cell1_events = [e for e in events if e.get("pid") == pids[1] and e["ph"] == "X"
                    and e.get("cat") == "host"]
    assert min(e["ts"] for e in cell1_events) >= 1e6
    # access lines render as searchable events carrying the trace id
    access = [e for e in events if e.get("cat") == "access"]
    assert {e["args"]["trace_id"] for e in access} == set(ids)
    fleet = [e for e in events if e.get("cat") == "fleet"]
    assert [e["name"] for e in fleet] == ["cell_launch", "cell_done"]


# ---------------------------------------------------------------------------
# obs_top: console frames over telemetry.jsonl and /metrics payloads
# ---------------------------------------------------------------------------


def _load_obs_top():
    spec = importlib.util.spec_from_file_location(
        "obs_top", os.path.join(REPO_ROOT, "scripts", "obs_top.py")
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def test_obs_top_run_dir_frame_cli(tmp_path):
    logs = tmp_path / "run" / "logs"
    logs.mkdir(parents=True)
    snapshot = {
        "ts": 1.0, "kind": "epoch", "session": "s1", "elapsed_s": 10.0,
        "steps": 20, "interval_episodes_per_s": 3.5, "mfu": 0.12,
        "phases": {"settle": {"p50_ms": 40.0, "p95_ms": 60.0, "count": 20}},
        "providers": {
            "memory": {"headroom_frac_min": 0.42},
            "watchdog": {"beat_age_s": 1.5},
        },
        "dropped_spans": 0,
    }
    with open(logs / "telemetry.jsonl", "w") as f:
        f.write(json.dumps({"kind": "step"}) + "\n")
        f.write(json.dumps(snapshot) + "\n")
        f.write('{"torn')  # hard-killed run: the console must not die
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts", "obs_top.py"),
         "--run-dir", str(tmp_path / "run"), "--once", "--json"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    frame = json.loads(proc.stdout)
    assert frame["source"] == "telemetry"
    assert frame["mfu"] == 0.12
    assert frame["episodes_per_s"] == 3.5
    assert frame["hbm_headroom_frac"] == 0.42
    assert frame["watchdog_beat_age_s"] == 1.5
    assert frame["phases"]["settle"]["p50_ms"] == 40.0


def test_obs_top_serving_frame_qps_and_render():
    obs_top = _load_obs_top()
    metrics = {
        "uptime_s": 12.0,
        "latency": {
            "adapt": {"p50_ms": 30.0, "p99_ms": 90.0, "count": 10},
            "predict": {"p50_ms": 5.0, "p99_ms": 20.0, "count": 30},
        },
        "adapt_batcher": {"queue_depth": 1},
        "predict_batcher": {"queue_depth": 2},
        "cache": {"hit_rate": 0.8},
        "resilience": {"shed": 3, "deadline_exceeded": 1,
                       "breaker": {"state": "closed", "opens": 0}},
        "prewarm": {"status": "warm"},
        "access_log": {"lines": 40},
        "memory": {"headroom_frac_min": 0.3},
    }
    first = obs_top.serving_frame(metrics, None, 2.0)
    assert first["qps"] is None and first["requests"] == 40
    later = dict(metrics)
    later["latency"] = {
        "adapt": {"p50_ms": 30.0, "p99_ms": 90.0, "count": 14},
        "predict": {"p50_ms": 5.0, "p99_ms": 20.0, "count": 46},
    }
    second = obs_top.serving_frame(later, first, 2.0)
    assert second["qps"] == 10.0  # (60 - 40) / 2s
    assert second["queue_depth"] == {"adapt": 1, "predict": 2}
    assert second["breaker"] == "closed" and second["shed"] == 3
    assert second["hbm_headroom_frac"] == 0.3
    rendered = obs_top.render(second)
    for token in ("qps 10", "breaker closed", "p99 90 ms", "hbm_headroom 0.3"):
        assert token in rendered, (token, rendered)
