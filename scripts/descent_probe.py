"""Repeated-batch descent probe: can the full meta-step (second order, MSL,
LSLR, outer Adam) descend on ONE fixed real 20-way batch?

Argv: [emulate 0/1] [n_way] [steps] [unroll 0/1, default 1]

`unroll=1` (default) compiles the SAME fully-unrolled second-order XLA
program family the production sweep runs use (sweep.sh leaves
unroll_inner_steps at its default True) — required when the probe's verdict
is about the platform's handling of that program. `unroll=0` is the rolled
variant (used for CPU arms, where the unrolled graph compiles too slowly).
`emulate=1` applies the shared bf16-operand MXU-default emulation from
grad_precision_probe.py (CPU arms only).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
import jax.numpy as jnp

emulate = int(sys.argv[1]) if len(sys.argv) > 1 else 0
n_way = int(sys.argv[2]) if len(sys.argv) > 2 else 20
steps = int(sys.argv[3]) if len(sys.argv) > 3 else 25
# emulation arms are CPU-only, where the unrolled 20-way graph compiles too
# slowly — default them to the rolled program; on-chip (emulate=0) arms
# default to the production unrolled program. Explicit 4th arg wins.
unroll = bool(int(sys.argv[4])) if len(sys.argv) > 4 else not emulate

if emulate:
    from grad_precision_probe import apply_mxu_default_emulation

    apply_mxu_default_emulation()

from howtotrainyourmamlpytorch_tpu.config import Config, DatasetConfig
from howtotrainyourmamlpytorch_tpu.core import MAMLSystem
from howtotrainyourmamlpytorch_tpu.data import MetaLearningDataLoader

cfg = Config(
    dataset=DatasetConfig(name="omniglot_dataset", path="datasets/omniglot_dataset"),
    num_classes_per_set=n_way,
    num_samples_per_class=1,
    num_target_samples=1,
    batch_size=4,
    load_into_memory=False,
    index_cache_dir="/tmp/omniglot_idx",
    unroll_inner_steps=unroll,
    remat_inner_steps=False,
)
loader = MetaLearningDataLoader(cfg, current_iter=0, data_root="/root/reference")
batch = next(iter(loader.train_batches(1, augment_images=True)))
batch = {k: jnp.asarray(v) for k, v in batch.items()}
system = MAMLSystem(cfg)
state = system.init_train_state()
print(
    f"emulate={emulate} n_way={n_way} unroll={unroll} backend={jax.default_backend()}",
    flush=True,
)
for i in range(steps):
    state, out = system.train_step(state, batch, epoch=0)
    if i % 10 == 0 or i == steps - 1:
        print(f"step {i:3d} loss={float(out.loss):.4f} acc={float(out.accuracy):.4f}", flush=True)
