"""Fused LSLR inner-update as a Pallas TPU kernel (the native-kernel proof
point promised by SURVEY.md §2.11/§7 stage 5).

The LSLR-generalized inner SGD step applies ``p <- p - lr_t * g`` with one
*learned scalar lr per parameter tensor* (reference one-param-group-per-tensor
trick, ``few_shot_learning_system.py:94-102``). Expressed over the pytree this
is one tiny elementwise op per leaf per inner step — dozens of kernel
dispatches of a few KB each, exactly the latency-bound regime the meta-step
profile shows. Here the whole pytree is packed once into a single
``[rows, 128]`` lane-aligned buffer (each leaf padded to full 128-lane rows)
and the update runs as ONE Pallas kernel: params and grads stream through VMEM
row-tiles while the per-row lr (gathered from the per-tensor lr vector by a
static row map) rides along as a ``[rows, 1]`` column.

Differentiability: the inner update must be differentiable w.r.t. params,
grads, AND the lrs (that is the whole LSLR point — meta-gradients flow into
the per-tensor lrs), including through the second-order rollout. The kernel
therefore carries a ``jax.custom_vjp``:

    forward:  out = p - lr * g
    backward: dp = ct;  dg = -lr * ct;  dlr_row = -sum_row(ct * g)

with the backward implemented as a second fused kernel; the per-row lr
cotangents reduce back to per-tensor lr cotangents through the (differentiable)
gather's transpose, i.e. a segment-sum handled by XLA outside the kernel.

Mixed precision (ops/precision.py bf16_inner policy): the packed param/grad
buffers keep whatever dtype the fast weights arrive in — bf16 operands stream
through VMEM at half the bytes, no upcast round-trip — while the lr column is
pinned to f32 (the LSLR lrs are f32 masters) and both kernels accumulate in
the lr's dtype: the forward computes ``p - lr*g`` in f32 and rounds once to
the operand dtype on store; the backward reduces the per-row lr cotangent
``-sum_row(ct * g)`` in f32, where a bf16 row-sum would lose exactly the
small-residual signal LSLR meta-learns from. With f32 operands everything
below is bit-identical to the pre-mixed-precision kernels.

Off-TPU (the CPU test mesh) the same kernels run in Pallas interpret mode, so
the suite exercises the identical code path everywhere.
"""

import functools
from typing import Any, List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .precision import as_f32

try:  # pltpu imports fail on builds without the TPU extension
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

LANE = 128  # TPU lane width: last dim of every tile
ROW_TILE = 256  # rows per grid step (256*128*4B = 128 KiB per operand block)


def _interpret() -> bool:
    return jax.default_backend() != "tpu" or not _HAS_PLTPU


class PackedLayout(NamedTuple):
    """Static description of the pytree -> [rows, 128] packing."""

    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    leaf_rows: Tuple[int, ...]  # 128-lane rows occupied by each leaf
    row_map: np.ndarray  # [padded_rows] int32: row -> leaf index
    rows: int  # unpadded total rows
    padded_rows: int  # rows rounded up to ROW_TILE


def build_layout(params) -> PackedLayout:
    leaves, treedef = jax.tree.flatten(params)
    shapes = tuple(tuple(l.shape) for l in leaves)
    leaf_rows = tuple(max(1, -(-l.size // LANE)) for l in leaves)
    rows = sum(leaf_rows)
    padded_rows = -(-rows // ROW_TILE) * ROW_TILE
    row_map = np.zeros((padded_rows,), np.int32)
    r = 0
    for i, n in enumerate(leaf_rows):
        row_map[r : r + n] = i
        r += n
    # padding rows keep leaf index 0; their lr values are read but the rows
    # are sliced away on unpack, so the value is irrelevant.
    return PackedLayout(treedef, shapes, leaf_rows, row_map, rows, padded_rows)


def pack(tree, layout: PackedLayout) -> jnp.ndarray:
    """Pytree -> [padded_rows, LANE] buffer (differentiable: pad + concat)."""
    leaves = jax.tree.leaves(tree)
    parts = []
    for leaf, n_rows in zip(leaves, layout.leaf_rows):
        flat = leaf.reshape(-1)
        flat = jnp.pad(flat, (0, n_rows * LANE - flat.size))
        parts.append(flat.reshape(n_rows, LANE))
    buf = jnp.concatenate(parts, axis=0)
    if layout.padded_rows != layout.rows:
        buf = jnp.pad(buf, ((0, layout.padded_rows - layout.rows), (0, 0)))
    return buf


def unpack(buf: jnp.ndarray, layout: PackedLayout):
    """[padded_rows, LANE] buffer -> pytree (differentiable: slice + reshape)."""
    leaves = []
    r = 0
    for shape, n_rows in zip(layout.shapes, layout.leaf_rows):
        size = int(np.prod(shape)) if shape else 1
        chunk = buf[r : r + n_rows].reshape(-1)[:size].reshape(shape)
        leaves.append(chunk)
        r += n_rows
    return jax.tree.unflatten(layout.treedef, leaves)


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------


def _fwd_kernel(p_ref, g_ref, lr_ref, out_ref):
    # accumulate in the lr's dtype (f32): bf16 operands upcast in-kernel,
    # one rounding on store; pure f32 traffic is untouched (astype no-ops)
    acc = lr_ref.dtype
    out_ref[:] = (p_ref[:].astype(acc) - lr_ref[:] * g_ref[:].astype(acc)).astype(
        out_ref.dtype
    )


def _bwd_kernel(ct_ref, g_ref, lr_ref, dg_ref, dlr_ref):
    acc = lr_ref.dtype
    ct = ct_ref[:].astype(acc)
    dg_ref[:] = (-lr_ref[:] * ct).astype(dg_ref.dtype)
    # the per-row lr cotangent is a 128-wide reduction of tiny products —
    # kept in f32 so the LSLR meta-gradient doesn't drown in bf16 rounding
    dlr_ref[:] = -jnp.sum(ct * g_ref[:].astype(acc), axis=1, keepdims=True)


def _row_specs(n: int):
    """n row-tiled [ROW_TILE, LANE] VMEM operands + one [ROW_TILE, 1] lr."""
    kwargs = {"memory_space": pltpu.VMEM} if _HAS_PLTPU and not _interpret() else {}
    wide = pl.BlockSpec((ROW_TILE, LANE), lambda i: (i, 0), **kwargs)
    narrow = pl.BlockSpec((ROW_TILE, 1), lambda i: (i, 0), **kwargs)
    return [wide] * n + [narrow]


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def _fused_sgd(p_buf, g_buf, lr_rows):
    return _fused_sgd_fwd_impl(p_buf, g_buf, lr_rows)


def _fused_sgd_fwd_impl(p_buf, g_buf, lr_rows):
    grid = (p_buf.shape[0] // ROW_TILE,)
    specs = _row_specs(2)
    return pl.pallas_call(
        _fwd_kernel,
        grid=grid,
        in_specs=specs,
        out_specs=pl.BlockSpec((ROW_TILE, LANE), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(p_buf.shape, p_buf.dtype),
        interpret=_interpret(),
    )(p_buf, g_buf, lr_rows)


def _fused_sgd_fwd(p_buf, g_buf, lr_rows):
    return _fused_sgd_fwd_impl(p_buf, g_buf, lr_rows), (g_buf, lr_rows)


def _fused_sgd_bwd(residuals, ct):
    g_buf, lr_rows = residuals
    grid = (g_buf.shape[0] // ROW_TILE,)
    specs = _row_specs(2)
    dg, dlr_rows = pl.pallas_call(
        _bwd_kernel,
        grid=grid,
        in_specs=specs,
        out_specs=[
            pl.BlockSpec((ROW_TILE, LANE), lambda i: (i, 0)),
            pl.BlockSpec((ROW_TILE, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(g_buf.shape, g_buf.dtype),
            jax.ShapeDtypeStruct((g_buf.shape[0], 1), lr_rows.dtype),
        ],
        interpret=_interpret(),
    )(ct, g_buf, lr_rows)
    return ct, dg, dlr_rows


_fused_sgd.defvjp(_fused_sgd_fwd, _fused_sgd_bwd)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def fused_sgd_update(params, grads, lr_tree, layout: PackedLayout = None):
    """One LSLR SGD step ``p - lr_t * g`` over the whole pytree as a single
    fused kernel. ``lr_tree`` holds one scalar per leaf (the learnable
    per-tensor lrs). Differentiable in all three inputs (custom VJP), so it
    composes with the second-order meta-gradient rollout."""
    layout = layout or build_layout(params)
    p_buf = pack(params, layout)
    g_buf = pack(grads, layout)
    lr_vec = jnp.stack([jnp.asarray(x).reshape(()) for x in jax.tree.leaves(lr_tree)])
    # static gather: per-row lr; its VJP (segment scatter-add) routes the
    # per-row lr cotangents from the kernel back to the per-tensor lrs. The
    # column is pinned to f32 — it is the kernels' accumulation dtype, and
    # the lrs are f32 masters even when p/g stream through as bf16.
    lr_rows = as_f32(lr_vec[jnp.asarray(layout.row_map)][:, None])
    out = _fused_sgd(p_buf, g_buf, lr_rows)
    return unpack(out, layout)
