"""Integration against the real Omniglot dataset (1,623 classes x 20 PNGs).

The dataset ships with the reference snapshot and is mounted read-only; these
tests exercise the full data path — reference-format index JSON interop
(reference ``data.py:241-276``), class-level ratio split (``data.py:197-218``),
episode assembly from real images (``data.py:486-532``) — and a short smoke
meta-training run on real episodes (SURVEY.md §4 integration tier).
"""

import os

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.config import Config, DatasetConfig
from howtotrainyourmamlpytorch_tpu.core import MAMLSystem
from howtotrainyourmamlpytorch_tpu.data import FewShotDataset, MetaLearningDataLoader
from howtotrainyourmamlpytorch_tpu.models import build_vgg

DATA_ROOT = "/root/reference"
DATA_PATH = os.path.join(DATA_ROOT, "datasets", "omniglot_dataset")

pytestmark = pytest.mark.skipif(
    not os.path.isdir(DATA_PATH), reason="real omniglot dataset not available"
)


def _cfg(**kw):
    defaults = dict(
        dataset=DatasetConfig(name="omniglot_dataset", path="datasets/omniglot_dataset"),
        num_classes_per_set=5,
        num_samples_per_class=1,
        num_target_samples=1,
        batch_size=4,
        load_into_memory=False,
        num_dataprovider_workers=2,
    )
    defaults.update(kw)
    return Config(**defaults)


@pytest.fixture(scope="module")
def omniglot():
    """Dataset over the read-only reference mount: the shipped index JSONs are
    read in place (no writes), relative paths resolved against the mount."""
    return FewShotDataset(_cfg(), data_root=DATA_ROOT)


def test_reference_index_interop_and_split_sizes(omniglot):
    sizes = {s: len(c) for s, c in omniglot.datasets.items()}
    # 1623 classes split by the reference ratios [0.709.., 0.0308.., 0.2606..]
    # (reference data.py:125): floor(0.70918*1623)=1150 train, val up to
    # floor(0.73999*1623)=1200 → 50, rest test.
    assert sum(sizes.values()) == 1623
    assert sizes["train"] == 1150
    assert sizes["val"] == 50
    assert sizes["test"] == 423
    # every class carries the full 20 drawings
    counts = {n for split in omniglot.class_counts.values() for n in split.values()}
    assert counts == {20}


def test_real_episode_contents(omniglot):
    ep = omniglot.sample_episode("train", omniglot.episode_seed("train", 0), augment=True)
    assert ep["x_support"].shape == (5, 1, 28, 28, 1)
    assert ep["x_target"].shape == (5, 1, 28, 28, 1)
    # omniglot is loaded as binary 0/1 floats, deliberately no /255
    # (reference data.py:382-403; SURVEY.md §2.4)
    values = np.unique(ep["x_support"])
    assert set(values).issubset({0.0, 1.0})
    # non-degenerate drawings: both ink and background present
    assert 0.0 < ep["x_support"].mean() < 1.0
    assert ep["y_support"].tolist() == [[0], [1], [2], [3], [4]]
    # determinism: same seed => identical episode
    ep2 = omniglot.sample_episode("train", omniglot.episode_seed("train", 0), augment=True)
    np.testing.assert_array_equal(ep["x_support"], ep2["x_support"])
    # different seed => different class draw (overwhelmingly likely over 1150)
    ep3 = omniglot.sample_episode("train", omniglot.episode_seed("train", 1), augment=True)
    assert not np.array_equal(ep["x_support"], ep3["x_support"])


def test_smoke_training_on_real_omniglot():
    """Short end-to-end meta-training on real Omniglot 5-way 1-shot: loss
    decreases and val accuracy beats chance by a wide margin within ~40
    meta-steps (SURVEY.md §4's integration check, scaled down for CI)."""
    cfg = _cfg(
        load_into_memory=True,
        number_of_training_steps_per_iter=3,
        number_of_evaluation_steps_per_iter=3,
        total_iter_per_epoch=50,
        multi_step_loss_num_epochs=10,
        meta_learning_rate=0.002,
    )
    ds = FewShotDataset(_cfg(), data_root=DATA_ROOT)
    # subset the class pools for CI speed, then pre-decode to RAM
    for split, n in (("train", 40), ("val", 16)):
        keys = list(ds.datasets[split])[:n]
        ds.datasets[split] = {k: ds.datasets[split][k] for k in keys}
        ds.class_counts[split] = {k: ds.class_counts[split][k] for k in keys}
    ds._load_into_memory()

    loader = MetaLearningDataLoader(cfg, dataset=ds)
    model = build_vgg(cfg.image_shape, cfg.num_classes_per_set, cnn_num_filters=8)
    system = MAMLSystem(cfg, model=model)
    state = system.init_train_state()

    first_losses, last_losses = [], []
    for i, batch in enumerate(loader.train_batches(40)):
        state, out = system.train_step(state, batch, epoch=0)
        (first_losses if i < 5 else last_losses).append(float(out.loss))

    val_accs = [
        float(system.eval_step(state, b).accuracy) for b in loader.val_batches(4)
    ]
    assert np.mean(last_losses[-5:]) < np.mean(first_losses)
    assert np.mean(val_accs) > 0.45  # chance is 0.2 for 5-way
